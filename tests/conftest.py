"""Test harness configuration.

The analog of the reference's ``tests/conftest.py`` + ``tests/unit/common.py``
device gating: unit tests run on a **virtual 8-device CPU mesh**
(``--xla_force_host_platform_device_count=8``) so the full suite runs without
TPUs — the same motivation as the reference's CPU CI lanes. The axon/TPU
plugin (when present) force-selects itself via ``jax.config``; we force the
platform back to cpu *before* any backend is initialized.
"""

import os

# Must happen before the first JAX backend initialization.
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " " + _flag
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_global_mesh():
    """Each test gets a fresh global mesh registry."""
    from deepspeed_tpu.parallel import mesh as mesh_mod

    mesh_mod.reset_mesh()
    yield
    mesh_mod.reset_mesh()


@pytest.fixture(autouse=True, scope="module")
def _bound_jax_compile_cache():
    """Clear JAX's jit/executable caches at module boundaries: a single
    process that accumulates ~400+ XLA:CPU compiled programs segfaults
    inside backend_compile_and_load (native compiler state — observed
    reproducibly at tests/unit/runtime/zero in monolithic runs while
    every chunked run passes). Cost: library-level jitted functions
    shared across test modules recompile after each boundary — accepted
    as the price of bounding native compiler state."""
    yield
    jax.clear_caches()


@pytest.fixture
def eight_device_mesh():
    from deepspeed_tpu.parallel import initialize_mesh

    return initialize_mesh()
