"""Test harness configuration.

The analog of the reference's ``tests/conftest.py`` + ``tests/unit/common.py``
device gating: unit tests run on a **virtual 8-device CPU mesh**
(``--xla_force_host_platform_device_count=8``) so the full suite runs without
TPUs — the same motivation as the reference's CPU CI lanes. The axon/TPU
plugin (when present) force-selects itself via ``jax.config``; we force the
platform back to cpu *before* any backend is initialized.
"""

import os

# Must happen before the first JAX backend initialization.
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " " + _flag
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_global_mesh():
    """Each test gets a fresh global mesh registry."""
    from deepspeed_tpu.parallel import mesh as mesh_mod

    mesh_mod.reset_mesh()
    yield
    mesh_mod.reset_mesh()


@pytest.fixture(autouse=True, scope="module")
def _bound_jax_compile_cache():
    """Clear JAX's jit/executable caches at module boundaries: a single
    process that accumulates ~400+ XLA:CPU compiled programs segfaults
    inside backend_compile_and_load (native compiler state — observed
    reproducibly at tests/unit/runtime/zero in monolithic runs while
    every chunked run passes). Cost: library-level jitted functions
    shared across test modules recompile after each boundary — accepted
    as the price of bounding native compiler state."""
    yield
    jax.clear_caches()


@pytest.fixture
def eight_device_mesh():
    from deepspeed_tpu.parallel import initialize_mesh

    return initialize_mesh()


@pytest.fixture
def tp_mesh():
    """Factory fixture for a ``(data, model)`` global mesh on the forced
    multi-device CPU host: ``mesh = tp_mesh(data=4, model=2)`` builds the
    mesh AND installs it as the process-global mesh (torn down by the
    autouse ``_reset_global_mesh``).

    This only works because of two environment settings made at the TOP
    of this conftest, before JAX initializes a backend — repeat them in
    any subprocess (bench arms, ``check_regression`` reruns) BEFORE its
    local ``import jax``:

    * ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` splits the
      host CPU into 8 virtual XLA devices. It is read once at backend
      initialization; exporting it after ``jax.devices()`` has run is a
      silent no-op and every mesh axis comes up size 1.
    * ``JAX_PLATFORMS=cpu`` must ride along: the forced host devices
      exist only on the ``cpu`` platform, so on a machine where an
      accelerator plugin force-selects itself the flag above would
      otherwise do nothing — the combination is what pins the 8-device
      topology tests rely on.
    """
    from deepspeed_tpu.parallel import mesh as mesh_mod

    def _make(data: int = 8, model: int = 1):
        mesh = mesh_mod.initialize_mesh(data=data, model=model)
        mesh_mod.set_mesh(mesh)
        return mesh

    return _make
