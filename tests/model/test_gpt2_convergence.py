"""E2E convergence harness — analog of reference ``tests/model/
Megatron_GPT2`` (run a real training config matrix and compare loss curves
against the baseline config). Uses a tiny GPT-2 on synthetic data so the
whole matrix runs in CI; the comparison logic mirrors
``tests/model/run_sanity_check.py``: every ZeRO/precision variant must
track the stage-0 fp32 curve within tolerance and reach a clearly lower
final loss than initial.
"""

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.parallel import mesh as mesh_mod

STEPS = 30
SEQ = 32
VOCAB = 97


def _data(batch_size, steps, seed=0):
    rng = np.random.default_rng(seed)
    # learnable structure: next token = (token * 3 + 1) % VOCAB with noise
    batches = []
    for _ in range(steps):
        start = rng.integers(0, VOCAB, (batch_size, 1))
        seqs = [start]
        for _ in range(SEQ - 1):
            nxt = (seqs[-1] * 3 + 1) % VOCAB
            seqs.append(nxt)
        ids = np.concatenate(seqs, axis=1).astype(np.int32)
        batches.append({"input_ids": ids})
    return batches


def _run(config_overrides, seed=0):
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel, gpt2_config

    mesh_mod.reset_mesh()
    cfg = gpt2_config("gpt2-125m", n_layer=2, n_head=2, n_embd=32,
                      vocab_size=VOCAB, n_positions=SEQ)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
        "gradient_clipping": 1.0,
        "steps_per_print": 10 ** 9,
        "seed": 1234,
    }
    config.update(config_overrides)
    engine, _, _, _ = ds.initialize(model=GPT2LMHeadModel(cfg),
                                    config=config)
    losses = []
    for batch in _data(engine.train_batch_size(), STEPS, seed):
        losses.append(float(engine.train_batch(batch=batch)))
    return np.asarray(losses)


@pytest.fixture(scope="module")
def baseline_curve():
    return _run({})


VARIANTS = {
    "zero1": {"zero_optimization": {"stage": 1}},
    "zero2_bf16": {"zero_optimization": {"stage": 2}, "bf16": {"enabled": True}},
    "zero3_bf16": {"zero_optimization": {"stage": 3}, "bf16": {"enabled": True}},
    "zero2_offload": {"zero_optimization": {"stage": 2,
                                            "offload_optimizer": {"device": "cpu"}},
                      "bf16": {"enabled": True}},
    "gas4": {"train_micro_batch_size_per_gpu": 2,
             "gradient_accumulation_steps": 4},
}


@pytest.mark.parametrize("name", sorted(VARIANTS))
def test_variant_tracks_baseline(name, baseline_curve):
    curve = _run(VARIANTS[name])
    assert curve[-1] < curve[0] * 0.8, \
        f"{name} did not learn: {curve[0]:.3f} -> {curve[-1]:.3f}"
    if name == "gas4":
        # different effective batch → only require learning
        return
    # final-quarter average must track the baseline curve (reference
    # run_sanity_check tolerance-style comparison)
    tail = curve[-STEPS // 4:].mean()
    base_tail = baseline_curve[-STEPS // 4:].mean()
    assert abs(tail - base_tail) / base_tail < 0.15, \
        f"{name}: tail {tail:.3f} vs baseline {base_tail:.3f}"


def test_baseline_learns(baseline_curve):
    assert baseline_curve[-1] < baseline_curve[0] * 0.6, baseline_curve
