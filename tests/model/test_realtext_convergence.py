"""Real-text convergence across the ZeRO/offload matrix (VERDICT r3 #4).

The reference's model-level e2e suite trains real Megatron GPT-2 on real
corpora and compares loss curves against baselines
(``tests/model/Megatron_GPT2/``, ``run_sanity_check.py``). The analog
here: a causal LM trained on REAL English prose — ~2.8 MB of
human-written documentation text harvested from installed packages,
committed as an xz fixture (zero-egress environments cannot fetch a
public corpus; this one is genuine natural language with the usual
Zipfian token statistics) — byte-level vocabulary, held-out validation
perplexity.

Matrix: fp32 baseline vs bf16 x {ZeRO-0, ZeRO-1, ZeRO-2,
offload_optimizer(cpu), offload_param(cpu streamed)} — every member's
loss CURVE must track the fp32 baseline within tolerance at each
checkpointed step (not just the endpoint), every member must improve
held-out perplexity, and the members must agree with each other.
"""

import lzma
import os

import numpy as np

import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import (
    TransformerLM,
    transformer_config,
)
from deepspeed_tpu.parallel import reset_mesh

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
SEQ = 128
STEPS = 30
BATCH_PER_RANK = 1  # x8 virtual devices = global batch 8


def _load(split: str) -> np.ndarray:
    with lzma.open(os.path.join(FIXTURES, f"realtext_{split}.txt.xz"),
                   "rt") as f:
        text = f.read()
    return np.frombuffer(text.encode("utf-8"), np.uint8)


def _batches(data: np.ndarray, batch: int, steps: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(steps):
        starts = rng.integers(0, len(data) - SEQ - 1, batch)
        out.append({"input_ids": np.stack(
            [data[s:s + SEQ] for s in starts]).astype(np.int32)})
    return out


def _model(dtype):
    return TransformerLM(transformer_config(
        "gpt2", vocab_size=256, max_seq_len=SEQ, n_embd=64, n_layer=2,
        n_head=4, dtype=dtype))


def _run(zero, dtype, batches, val_batches):
    reset_mesh()
    conf = {"train_micro_batch_size_per_gpu": BATCH_PER_RANK,
            "gradient_accumulation_steps": 1,
            "zero_optimization": zero,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 3e-3, "weight_decay": 0.01}},
            "gradient_clipping": 1.0, "steps_per_print": 10 ** 9}
    if dtype == jnp.bfloat16:
        conf["bf16"] = {"enabled": True}
    engine, _, _, _ = ds.initialize(model=_model(dtype), config=conf)
    curve = [float(engine.train_batch(batch=b)) for b in batches]

    if engine._param_offload is not None:
        val_losses = [engine._param_offload.eval_loss(b)
                      for b in val_batches]
    else:
        eval_fn = engine.eval_batch_fn()
        val_losses = [float(eval_fn(engine.state["params"], b))
                      for b in val_batches]
    ppl = float(np.exp(np.mean(val_losses)))
    return curve, ppl


def test_realtext_matrix_tracks_fp32_baseline():
    train = _load("train")
    val = _load("val")
    batches = _batches(train, BATCH_PER_RANK * 8, STEPS)
    val_batches = _batches(val, 8, 4, seed=99)

    base_curve, base_ppl = _run({"stage": 0}, jnp.float32, batches,
                                val_batches)
    # the fp32 baseline itself must LEARN real text: loss falls and
    # held-out perplexity beats the uniform-byte ceiling (256) by a lot
    assert base_curve[-1] < base_curve[0] - 0.5, base_curve
    assert base_ppl < 60, base_ppl

    matrix = {
        "bf16_z0": ({"stage": 0}, jnp.bfloat16),
        "bf16_z1": ({"stage": 1}, jnp.bfloat16),
        "bf16_z2": ({"stage": 2}, jnp.bfloat16),
        "bf16_offload_opt": ({"stage": 2, "offload_optimizer":
                              {"device": "cpu"}}, jnp.bfloat16),
        "bf16_offload_param": ({"offload_param": {"device": "cpu"}},
                               jnp.bfloat16),
    }
    ppls = {}
    for name, (zero, dtype) in matrix.items():
        curve, ppl = _run(zero, dtype, batches, val_batches)
        ppls[name] = ppl
        # curve tolerance vs the fp32 baseline at EVERY recorded step:
        # bf16 rounding accumulates, so the band widens with step index
        for i, (a, b) in enumerate(zip(base_curve, curve)):
            tol = 0.05 + 0.01 * i
            assert abs(a - b) < tol, (name, i, a, b)
        assert curve[-1] < curve[0] - 0.5, (name, curve)
        # held-out perplexity within a band of the fp32 baseline
        assert abs(np.log(ppl) - np.log(base_ppl)) < 0.15, (name, ppl,
                                                            base_ppl)
    # matrix members agree with each other too
    vals = sorted(ppls.values())
    assert vals[-1] / vals[0] < 1.3, ppls
