"""Elastic training closed end-to-end (VERDICT r2 next #8).

One composition test covering the loop the reference's elastic machinery
exists for (``deepspeed/elasticity/elastic_agent.py:28`` +
``checkpoint/universal_checkpoint.py:12``):

  2-proc launch via the CLI launcher → a worker dies mid-training → the
  elastic agent restarts the job → training resumes from the checkpoint →
  the job is then relaunched at a DIFFERENT world size resuming from the
  UNIVERSAL checkpoint → the loss continues where it left off.

The phases run as real subprocess launches of ``deepspeed_tpu.launcher
.runner`` (CPU backend, Gloo rendezvous); continuity is asserted through a
fixed probe batch whose loss must be preserved across kill + restart +
re-mesh, plus the recorded loss trajectory.
"""

import json
import os
import subprocess
import sys
import textwrap

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", ".."))

_TRAIN_SCRIPT = r"""
import json
import os
import sys

import jax
jax.config.update("jax_platforms", "cpu")  # before any backend use

import numpy as np

work = sys.argv[1]
mode = sys.argv[2]                  # "train" | "resume_universal"
total_steps = int(sys.argv[3])
kill_at = int(sys.argv[4])          # rank 1 dies after this step on 1st run
rank = int(os.environ.get("RANK", "0"))
world = int(os.environ.get("WORLD_SIZE", "1"))

import deepspeed_tpu as ds

ds.init_distributed()

from deepspeed_tpu.models.transformer_lm import (
    TransformerConfig,
    TransformerLM,
)

GLOBAL_BATCH = 4
ckpt = os.path.join(work, "ckpt")


def make_batch(step):
    # ONE fixed batch for every step: the loss then decreases monotonically
    # (memorization), so trajectory continuity across kill/restart/re-mesh
    # is directly assertable
    rng = np.random.default_rng(1000)
    return {"input_ids": rng.integers(0, 64, (GLOBAL_BATCH, 32)).astype(np.int32)}


def probe_loss(engine):
    rng = np.random.default_rng(7)
    batch = {"input_ids": rng.integers(0, 64, (GLOBAL_BATCH, 32)).astype(np.int32)}
    params = jax.device_get(engine.state["params"])
    return float(engine.module.apply(
        {"params": params}, {"input_ids": np.asarray(batch["input_ids"])},
        deterministic=True))


def record(payload):
    if rank == 0:
        with open(os.path.join(work, "losses.jsonl"), "a") as f:
            f.write(json.dumps(payload) + "\n")


model = TransformerLM(TransformerConfig(
    vocab_size=64, n_embd=32, n_layer=2, n_head=4, max_seq_len=32))
engine, _, _, _ = ds.initialize(
    model=model,
    config={"train_micro_batch_size_per_gpu": GLOBAL_BATCH // world,
            "gradient_accumulation_steps": 1,
            "zero_optimization": {"stage": 1},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "steps_per_print": 10 ** 9})

# per-rank start counter — distinguishes the pre-kill attempt from the
# agent's restart
marker = os.path.join(work, f"starts_rank{rank}")
starts = int(open(marker).read()) if os.path.exists(marker) else 0
open(marker, "w").write(str(starts + 1))

if mode == "resume_universal":
    from deepspeed_tpu.checkpoint import ds_to_universal

    if rank == 0:
        ds_to_universal(ckpt)
    engine.train_batch(batch=make_batch(0))       # build state (overwritten)
    engine.load_universal_checkpoint(ckpt)
    with open(os.path.join(work, "probe_after_remesh.json"), "w") as f:
        json.dump({"probe": probe_loss(engine),
                   "resumed_step": engine.global_steps, "world": world}, f)
elif os.path.exists(os.path.join(ckpt, "latest")):
    engine.train_batch(batch=make_batch(0))       # build state (overwritten)
    engine.load_checkpoint(ckpt)

while engine.global_steps < total_steps:
    step = engine.global_steps
    loss = float(engine.train_batch(batch=make_batch(step)))
    record({"mode": mode, "world": world, "attempt": starts,
            "step": engine.global_steps, "loss": loss})
    engine.save_checkpoint(ckpt)
    if mode == "train" and rank == 1 and starts == 0 and \
            engine.global_steps == kill_at:
        os._exit(1)                               # simulated worker death

if mode == "train" and rank == 0:
    with open(os.path.join(work, "probe_after_train.json"), "w") as f:
        json.dump({"probe": probe_loss(engine),
                   "final_step": engine.global_steps}, f)
"""


def _free_port() -> int:
    """An ephemeral port from the OS — fixed ports collide under parallel
    test execution (xdist / concurrent CI jobs on one host)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(script, work, mode, total, kill_at, nprocs, port, elastic=False):
    cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.runner",
           "--num_gpus", str(nprocs), "--master_port", str(port)]
    if elastic:
        cmd += ["--elastic_training", "--max_elastic_restarts", "2"]
    cmd += [script, work, mode, str(total), str(kill_at)]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # no virtual-mesh leak into real procs
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(cmd, capture_output=True, text=True, timeout=900,
                          cwd=REPO_ROOT, env=env)


def test_elastic_loop_end_to_end(tmp_path):
    script = tmp_path / "elastic_train.py"
    script.write_text(textwrap.dedent(_TRAIN_SCRIPT))
    work = str(tmp_path)

    # Phase A: 2 workers, elastic agent on; rank 1 dies after step 2 on the
    # first attempt; the agent restarts and training resumes to step 4.
    proc = _launch(str(script), work, "train", 4, 2, nprocs=2, port=_free_port(),
                   elastic=True)
    assert proc.returncode == 0, proc.stderr[-4000:]

    starts0 = int((tmp_path / "starts_rank0").read_text())
    starts1 = int((tmp_path / "starts_rank1").read_text())
    assert (starts0, starts1) == (2, 2), \
        f"agent restart did not happen: starts={starts0, starts1}"

    rows = [json.loads(l) for l in
            (tmp_path / "losses.jsonl").read_text().splitlines()]
    attempt0 = [r["step"] for r in rows if r["attempt"] == 0]
    attempt1 = [r["step"] for r in rows if r["attempt"] == 1 and
                r["mode"] == "train"]
    assert attempt0 == [1, 2], attempt0          # trained to the kill point
    assert attempt1 == [3, 4], attempt1          # resumed, not restarted at 0

    probe_a = json.loads((tmp_path / "probe_after_train.json").read_text())
    assert probe_a["final_step"] == 4

    # Phase B: relaunch at world size 1 from the universal checkpoint.
    proc = _launch(str(script), work, "resume_universal", 6, -1, nprocs=1,
                   port=_free_port())
    assert proc.returncode == 0, proc.stderr[-4000:]

    probe_b = json.loads((tmp_path / "probe_after_remesh.json").read_text())
    assert probe_b["resumed_step"] == 4, probe_b  # step counter survived
    assert probe_b["world"] == 1
    # weights survived kill + restart + re-mesh: same probe batch, same loss
    assert abs(probe_b["probe"] - probe_a["probe"]) < 5e-3, (probe_a, probe_b)

    # loss continuity: the re-meshed run continues the trajectory
    rows = [json.loads(l) for l in
            (tmp_path / "losses.jsonl").read_text().splitlines()]
    resumed = [r for r in rows if r["mode"] == "resume_universal"]
    assert [r["step"] for r in resumed] == [5, 6], resumed
    assert all(np.isfinite(r["loss"]) for r in resumed)
    # single fixed batch -> the whole trajectory (across the kill, the
    # restart, and the re-mesh) must be monotonically decreasing
    train_rows = sorted((r for r in rows if r["mode"] == "train"),
                        key=lambda r: r["step"])
    trajectory = [r["loss"] for r in train_rows + resumed]
    assert all(b < a + 1e-3 for a, b in zip(trajectory, trajectory[1:])), \
        trajectory
    assert trajectory[-1] < trajectory[0], trajectory


import numpy as np  # noqa: E402  (used in assertions)
