"""Hostfile parsing + resource filtering — analog of reference
``tests/unit/launcher/test_run.py``."""

import pytest

from deepspeed_tpu.launcher.runner import (
    encode_world_info,
    fetch_hostfile,
    parse_inclusion_exclusion,
)


def write_hostfile(tmp_path, content):
    p = tmp_path / "hostfile"
    p.write_text(content)
    return str(p)


def test_parse_hostfile(tmp_path):
    path = write_hostfile(tmp_path, "worker-1 slots=4\nworker-2 slots=4\n")
    pool = fetch_hostfile(path)
    assert pool == {"worker-1": 4, "worker-2": 4}


def test_parse_hostfile_comments_and_blanks(tmp_path):
    path = write_hostfile(
        tmp_path, "# a comment\n\nworker-1 slots=2\n  \nworker-2 slots=8\n")
    pool = fetch_hostfile(path)
    assert pool == {"worker-1": 2, "worker-2": 8}


def test_parse_hostfile_bad_line(tmp_path):
    path = write_hostfile(tmp_path, "worker-1 slots=4\nbadline\n")
    with pytest.raises(ValueError):
        fetch_hostfile(path)


def test_parse_hostfile_duplicate(tmp_path):
    path = write_hostfile(tmp_path, "w1 slots=4\nw1 slots=2\n")
    with pytest.raises(ValueError):
        fetch_hostfile(path)


def test_missing_hostfile_returns_none(tmp_path):
    assert fetch_hostfile(str(tmp_path / "nope")) is None


def test_include_filter():
    pool = {"w1": 4, "w2": 4, "w3": 4}
    active = parse_inclusion_exclusion(pool, "w1@w2:0,2", "")
    assert active == {"w1": [0, 1, 2, 3], "w2": [0, 2]}


def test_exclude_filter():
    pool = {"w1": 4, "w2": 4}
    active = parse_inclusion_exclusion(pool, "", "w1")
    assert active == {"w2": [0, 1, 2, 3]}


def test_exclude_slots():
    pool = {"w1": 4}
    active = parse_inclusion_exclusion(pool, "", "w1:1,3")
    assert active == {"w1": [0, 2]}


def test_include_unknown_host_raises():
    with pytest.raises(ValueError):
        parse_inclusion_exclusion({"w1": 4}, "nope", "")


def test_world_info_roundtrip():
    import base64
    import json

    info = {"w1": [0, 1], "w2": [0]}
    b64 = encode_world_info(info)
    assert json.loads(base64.urlsafe_b64decode(b64)) == info
