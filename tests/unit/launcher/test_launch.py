"""End-to-end single-node launch through the CLI — the analog of the
reference's launcher integration tests."""

import subprocess
import sys
import textwrap


def test_single_node_launch_sets_env(tmp_path):
    script = tmp_path / "train.py"
    out = tmp_path / "env.txt"
    script.write_text(textwrap.dedent(f"""
        import os
        with open({str(out)!r}, "a") as f:
            f.write(os.environ["RANK"] + " " + os.environ["WORLD_SIZE"] +
                    " " + os.environ["MASTER_ADDR"] + "\\n")
    """))
    proc = subprocess.run(
        [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.runner",
         "--num_gpus", "2", "--master_port", "29511", str(script)],
        capture_output=True, text=True, timeout=120, cwd="/root/repo")
    assert proc.returncode == 0, proc.stderr
    lines = sorted(out.read_text().strip().splitlines())
    assert lines == ["0 2 127.0.0.1", "1 2 127.0.0.1"]


def test_failing_rank_propagates_exit_code(tmp_path):
    script = tmp_path / "boom.py"
    script.write_text("import sys; sys.exit(7)")
    proc = subprocess.run(
        [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.runner",
         "--master_port", "29512", str(script)],
        capture_output=True, text=True, timeout=120, cwd="/root/repo")
    assert proc.returncode == 7


def test_elastic_launch_restarts(tmp_path):
    marker = tmp_path / "attempts"
    script = tmp_path / "flaky.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        p = {str(marker)!r}
        n = int(open(p).read()) if os.path.exists(p) else 0
        open(p, "w").write(str(n + 1))
        sys.exit(0 if n >= 1 else 1)
    """))
    proc = subprocess.run(
        [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.runner",
         "--elastic_training", "--max_elastic_restarts", "2",
         "--master_port", "29513", str(script)],
        capture_output=True, text=True, timeout=120, cwd="/root/repo")
    assert proc.returncode == 0, proc.stderr
    assert int(marker.read_text()) == 2
