"""Generated multi-node command lines — analog of reference
``tests/unit/launcher/test_multinode_runner.py``."""

import pytest

from deepspeed_tpu.launcher import multinode_runner as mnrunner
from deepspeed_tpu.launcher.runner import encode_world_info, parse_args


@pytest.fixture
def runner_args():
    return parse_args(["--master_addr", "10.0.0.1", "test_script.py",
                       "--arg1", "val1"])


@pytest.fixture
def world_info():
    return encode_world_info({"w1": [0, 1], "w2": [0, 1]})


def test_pdsh_runner(runner_args, world_info):
    runner = mnrunner.PDSHRunner(runner_args, world_info)
    cmd = runner.get_cmd({}, {"w1": [0, 1], "w2": [0, 1]})
    assert cmd[0] == "pdsh"
    assert "-w" in cmd
    assert "w1,w2" in cmd
    joined = " ".join(cmd)
    assert "deepspeed_tpu.launcher.launch" in joined
    assert "--node_rank=%n" in joined
    assert "--master_addr=10.0.0.1" in joined
    assert "test_script.py" in joined


def test_openmpi_runner(runner_args, world_info):
    runner = mnrunner.OpenMPIRunner(runner_args, world_info,
                                    {"w1": [0, 1], "w2": [0, 1]})
    cmd = runner.get_cmd({}, {"w1": [0, 1], "w2": [0, 1]})
    assert cmd[0] == "mpirun"
    assert "-n" in cmd
    assert "4" in cmd
    assert "test_script.py" in cmd


def test_mpich_runner(runner_args, world_info):
    # resource pool values are slot-id LISTS — the shape runner.main() passes
    runner = mnrunner.MPICHRunner(runner_args, world_info,
                                  {"w1": [0, 1], "w2": [0, 1]})
    cmd = runner.get_cmd({}, {})
    assert cmd[0] == "mpirun"
    assert "-ppn" in cmd
    assert cmd[cmd.index("-n") + 1] == "4"
    assert cmd[cmd.index("-ppn") + 1] == "2"
    assert "test_script.py" in cmd


def test_mpich_runner_mismatched_slots(runner_args, world_info):
    runner = mnrunner.MPICHRunner(runner_args, world_info,
                                  {"w1": [0, 1], "w2": [0]})
    with pytest.raises(ValueError):
        runner.get_cmd({}, {})


def test_impi_runner(runner_args, world_info):
    runner = mnrunner.IMPIRunner(runner_args, world_info,
                                 {"w1": [0, 1], "w2": [0, 1]})
    cmd = runner.get_cmd({}, {})
    assert cmd[0] == "mpirun"
    joined = " ".join(cmd)
    assert "MASTER_ADDR" in joined
    assert "10.0.0.1" in joined
    assert "WORLD_SIZE 4" in joined
    assert "LOCAL_SIZE 2" in joined


def test_slurm_runner(runner_args, world_info):
    runner = mnrunner.SlurmRunner(runner_args, world_info,
                                  {"w1": [0, 1], "w2": [0, 1]})
    cmd = runner.get_cmd({}, {})
    assert cmd[0] == "srun"
    assert "test_script.py" in cmd


def test_exports_propagate(runner_args, world_info):
    runner = mnrunner.PDSHRunner(runner_args, world_info)
    runner.add_export("XLA_FLAGS", "--xla_foo=1")
    cmd = runner.get_cmd({}, {"w1": [0]})
    assert "XLA_FLAGS" in " ".join(cmd)
