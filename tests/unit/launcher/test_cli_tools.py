"""Smoke tests for the bin/ CLI tools (ds_bench, ds_elastic, ds_report) —
the analog of the reference's bin-script coverage."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def _run(script, *args, timeout=240):
    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep +
               os.environ.get("PYTHONPATH", ""),
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=os.environ.get("XLA_FLAGS", "") +
               " --xla_force_host_platform_device_count=8")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", script), *args],
        capture_output=True, text=True, timeout=timeout, env=env)


def test_ds_bench_smoke():
    proc = _run("ds_bench", "--ops", "all_reduce", "--minsize", "15",
                "--maxsize", "15", "--trials", "2", "--warmups", "1")
    assert proc.returncode == 0, proc.stderr
    assert "all_reduce" in proc.stdout
    assert "algbw" in proc.stdout


def test_ds_elastic_smoke(tmp_path):
    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps({"elasticity": {
        "enabled": True, "max_train_batch_size": 1000,
        "micro_batch_sizes": [2, 4], "min_gpus": 1, "max_gpus": 100,
        "version": 0.1}}))
    proc = _run("ds_elastic", "-c", str(cfg), "-w", "4")
    assert proc.returncode == 0, proc.stderr
    assert "compatible chip counts" in proc.stdout
    assert "micro_batch=4" in proc.stdout  # deterministic for this config


def test_ds_report_smoke():
    proc = _run("ds_report", timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert "jax" in proc.stdout
    assert "ds_cpu_adam" in proc.stdout
