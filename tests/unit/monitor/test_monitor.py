"""Monitor suite — analog of reference ``tests/unit/monitor/test_monitor.py``
(MonitorMaster fan-out, per-backend writers, rank gating, engine wiring)."""

import csv
import os

from deepspeed_tpu.monitor.monitor import (
    MonitorMaster,
    TensorBoardMonitor,
    csvMonitor,
)
from deepspeed_tpu.runtime.config import MonitorConfig


def _cfg(tmp_path, tb=False, csv_on=False):
    return MonitorConfig(
        tensorboard={"enabled": tb, "output_path": str(tmp_path / "tb"),
                     "job_name": "job"},
        csv_monitor={"enabled": csv_on, "output_path": str(tmp_path / "csv"),
                     "job_name": "job"})


def test_monitor_config_enabled_property(tmp_path):
    assert not _cfg(tmp_path).enabled
    assert _cfg(tmp_path, csv_on=True).enabled
    assert _cfg(tmp_path, tb=True).enabled


def test_csv_monitor_writes_rows(tmp_path):
    cfg = _cfg(tmp_path, csv_on=True)
    mon = csvMonitor(cfg.csv_monitor)
    mon.write_events([("Train/loss", 1.5, 1), ("Train/lr", 0.1, 1)])
    mon.write_events([("Train/loss", 1.2, 2)])
    path = tmp_path / "csv" / "job" / "Train_loss.csv"
    with open(path, newline="") as fh:
        rows = list(csv.reader(fh))
    assert rows[0] == ["step", "Train/loss"]
    assert rows[1] == ["1", "1.5"]
    assert rows[2] == ["2", "1.2"]
    assert (tmp_path / "csv" / "job" / "Train_lr.csv").exists()


def test_master_fans_out_to_enabled_backends(tmp_path):
    cfg = _cfg(tmp_path, csv_on=True)
    master = MonitorMaster(cfg)
    assert master.enabled
    assert master.csv_monitor is not None
    assert master.wandb_monitor is None  # not enabled → never constructed
    master.write_events([("a/b", 3.0, 7)])
    assert (tmp_path / "csv" / "job" / "a_b.csv").exists()


def test_master_disabled_writes_nothing(tmp_path):
    master = MonitorMaster(_cfg(tmp_path))
    assert not master.enabled
    master.write_events([("x", 1.0, 1)])
    assert not (tmp_path / "csv").exists()


def test_tensorboard_monitor_gates_on_import(tmp_path):
    """When torch tensorboard is importable it writes event files; when it
    is not, the monitor disables itself instead of crashing."""
    cfg = _cfg(tmp_path, tb=True)
    mon = TensorBoardMonitor(cfg.tensorboard)
    if mon.enabled:
        mon.write_events([("Train/loss", 2.0, 1)])
        logdir = tmp_path / "tb" / "job"
        assert any(f.startswith("events") for f in os.listdir(logdir))
    else:
        mon.write_events([("Train/loss", 2.0, 1)])  # no-op, no raise


def test_engine_emits_monitor_events(tmp_path):
    """steps_per_print-gated engine events land in the CSV backend
    (reference engine.py:2153 _write_monitor path)."""
    import deepspeed_tpu as ds
    from tests.unit.simple_model import SimpleModel, base_config, random_batch

    cfg = base_config(extra={
        "steps_per_print": 1,
        "csv_monitor": {"enabled": True,
                        "output_path": str(tmp_path / "csv"),
                        "job_name": "engine"}})
    engine, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                    config=cfg)
    b = random_batch(engine.train_batch_size())
    for _ in range(3):
        engine.train_batch(batch=b)
    outdir = tmp_path / "csv" / "engine"
    assert outdir.exists(), "engine wrote no monitor events"
    files = os.listdir(outdir)
    assert any("loss" in f.lower() for f in files), files
