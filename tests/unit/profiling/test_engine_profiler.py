"""Engine-integrated flops profiling (reference engine.py:1688 +
tests/unit/inference/test_model_profiling.py analog)."""

import numpy as np

import deepspeed_tpu as ds


def test_engine_profiles_at_step(tmp_path, capsys):
    from tests.unit.simple_model import SimpleModel

    out = str(tmp_path / "flops.txt")
    model = SimpleModel(hidden_dim=32)
    dim = 16
    config = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "flops_profiler": {"enabled": True, "profile_step": 1,
                           "output_file": out},
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rng = np.random.default_rng(0)

    def batch():
        return {"x": rng.standard_normal((engine.train_batch_size(), dim),
                                         dtype=np.float32),
                "y": rng.standard_normal((engine.train_batch_size(),),
                                         dtype=np.float32)}

    for _ in range(3):
        engine.train_batch(batch=batch())
    with open(out) as f:
        report = f.read()
    assert "Flops Profiler" in report
    assert "FLOPs" in report
