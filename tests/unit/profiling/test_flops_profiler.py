"""Flops-profiler tests — analog of reference
``tests/unit/profiling/test_flops_profiler.py`` (asserts computed flops are
within tolerance of the analytic count)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.profiling import FlopsProfiler, get_model_profile

TOL = 0.10


class SimpleMLP(nn.Module):
    hidden: int = 64
    out: int = 32

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.hidden, use_bias=False)(x)
        x = nn.relu(x)
        x = nn.Dense(self.out, use_bias=False)(x)
        return x


def within_range(v, target, tolerance=TOL):
    return abs(v - target) / max(target, 1) < tolerance


def test_matmul_flops_exact():
    a = jnp.ones((8, 16), jnp.float32)
    b = jnp.ones((16, 24), jnp.float32)

    prof = FlopsProfiler()
    prof.start_profile()
    res = prof.profile(lambda x, y: x @ y, a, b, run=False)
    assert res["macs"] == 8 * 16 * 24
    assert res["flops"] == 2 * 8 * 16 * 24


def test_mlp_flops_within_tolerance():
    model = SimpleMLP()
    batch, din = 4, 128
    x = jnp.ones((batch, din), jnp.float32)
    rng = jax.random.PRNGKey(0)
    params = model.init(rng, x)["params"]

    prof = FlopsProfiler(model=model)
    prof.start_profile()
    res = prof.profile(lambda p, xx: model.apply({"params": p}, xx), params, x)
    analytic = 2 * batch * (din * 64 + 64 * 32)
    # relu + minor elementwise on top of the matmul flops
    assert res["flops"] >= analytic
    assert within_range(res["flops"], analytic, 0.15)
    assert res["params"] == din * 64 + 64 * 32
    assert res["duration"] > 0


def test_scan_flops_scale_with_length():
    w = jnp.ones((32, 32), jnp.float32)

    def scanned(x):
        def body(carry, _):
            return carry @ w, None

        y, _ = jax.lax.scan(body, x, None, length=10)
        return y

    prof = FlopsProfiler()
    prof.start_profile()
    res = prof.profile(scanned, jnp.ones((4, 32), jnp.float32), run=False)
    assert res["macs"] == 10 * 4 * 32 * 32


def test_named_scope_tree_attribution():
    model = SimpleMLP()
    x = jnp.ones((2, 16), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]

    prof = FlopsProfiler(model=model)
    prof.start_profile()
    prof.profile(lambda p, xx: model.apply({"params": p}, xx), params, x,
                 run=False)
    scopes = [k for k in prof._tree if "Dense" in k]
    assert scopes, f"expected Dense scopes in tree, got {list(prof._tree)}"
    report = prof.print_model_profile(detailed=True)
    assert "Dense" in report
    assert "FLOPs" in report


def test_get_model_profile():
    model = SimpleMLP()
    x = jnp.ones((2, 16), jnp.float32)
    flops, macs, params = get_model_profile(model, args=(x,),
                                            print_profile=False)
    assert macs == 2 * (16 * 64 + 64 * 32)
    assert params == 16 * 64 + 64 * 32


def test_training_step_flops_roughly_3x_forward():
    """grad-of-loss ≈ 2-3× fwd matmul flops (dx of the first layer is not
    materialized since the input is not differentiated)."""
    model = SimpleMLP()
    x = jnp.ones((4, 128), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]

    def loss_fn(p, xx):
        return jnp.mean(model.apply({"params": p}, xx) ** 2)

    prof = FlopsProfiler()
    prof.start_profile()
    fwd = prof.profile(lambda p, xx: model.apply({"params": p}, xx), params, x,
                       run=False)
    prof.reset_profile()
    step = prof.profile(jax.grad(loss_fn), params, x, run=False)
    ratio = step["macs"] / fwd["macs"]
    assert 2.0 <= ratio <= 3.5, ratio
