"""OnDevice, tensor-fragment APIs, state-dict factory, env report —
analogs of reference ``tests/unit/utils/`` + ``test_sd_loader``-style
coverage."""

import numpy as np
import pytest

import deepspeed_tpu as ds


class TestOnDevice:
    def test_meta_init_no_memory(self):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.utils.init_on_device import OnDevice

        class Big(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(128)(x)

        with OnDevice(dtype=jnp.bfloat16, device="meta") as ctx:
            shapes = ctx.abstract_init(Big(), jnp.ones((1, 64)))
        kernel = shapes["params"]["Dense_0"]["kernel"]
        assert isinstance(kernel, jax.ShapeDtypeStruct)
        assert kernel.shape == (64, 128)
        assert kernel.dtype == jnp.bfloat16

    def test_concrete_device(self):
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.utils.init_on_device import OnDevice

        dev = jax.devices()[1]
        with OnDevice(device=dev):
            x = jnp.ones((4,))
        assert list(x.devices())[0] == dev


class TestTensorFragment:
    def _engine(self, offload=False):
        from deepspeed_tpu.parallel import mesh as mesh_mod
        from tests.unit.simple_model import SimpleModel

        mesh_mod.reset_mesh()
        config = {
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2},
            "bf16": {"enabled": True},
            "steps_per_print": 1000,
        }
        if offload:
            config["zero_optimization"]["offload_optimizer"] = \
                {"device": "cpu"}
        engine, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                        config=config)
        return engine

    def test_safe_get_param_and_opt_state(self):
        from deepspeed_tpu.utils.tensor_fragment import (
            safe_get_full_fp32_param,
            safe_get_full_optimizer_state,
        )
        from tests.unit.simple_model import random_batch

        engine = self._engine()
        b = random_batch(engine.train_batch_size())
        for _ in range(2):
            engine.train_batch(batch=b)
        p = safe_get_full_fp32_param(engine, "linear_0.kernel")
        assert p is not None and p.dtype == np.float32
        assert p.shape == (16, 16)
        m = safe_get_full_optimizer_state(engine, "linear_0.kernel",
                                          "exp_avg")
        assert m is not None and np.abs(m).sum() > 0

    def test_safe_get_grad_eager_path(self):
        from deepspeed_tpu.utils.tensor_fragment import safe_get_full_grad
        from tests.unit.simple_model import random_batch

        engine = self._engine()
        b = random_batch(engine.train_batch_size())
        assert safe_get_full_grad(engine, "linear_0.kernel") is None
        loss = engine.forward(b)
        engine.backward(loss)
        g = safe_get_full_grad(engine, "linear_0.kernel")
        assert g is not None and np.abs(g).sum() > 0
        engine.step()

    def test_safe_set_param(self):
        from deepspeed_tpu.utils.tensor_fragment import (
            safe_get_full_fp32_param,
            safe_set_full_fp32_param,
        )
        from tests.unit.simple_model import random_batch

        engine = self._engine()
        engine.train_batch(batch=random_batch(engine.train_batch_size()))
        new = np.full((16, 16), 0.5, np.float32)
        assert safe_set_full_fp32_param(engine, "linear_0.kernel", new)
        got = safe_get_full_fp32_param(engine, "linear_0.kernel")
        np.testing.assert_allclose(got, new)

    def test_offload_paths(self):
        from deepspeed_tpu.utils.tensor_fragment import (
            safe_get_full_fp32_param,
            safe_get_full_optimizer_state,
        )
        from tests.unit.simple_model import random_batch

        engine = self._engine(offload=True)
        b = random_batch(engine.train_batch_size())
        for _ in range(2):
            engine.train_batch(batch=b)
        p = safe_get_full_fp32_param(engine, "linear_0.kernel")
        assert p is not None and p.shape == (16, 16)
        m = safe_get_full_optimizer_state(engine, "linear_0.kernel",
                                          "exp_avg")
        assert m is not None and m.shape == (16, 16)


class TestSDLoader:
    def _make_shards(self, tmp_path, n=2, hidden=8, version=2.0):
        rng = np.random.default_rng(0)
        paths = []
        for i in range(n):
            sd = {
                "attention.query_key_value.weight":
                    rng.standard_normal((3 * hidden // n, hidden))
                    .astype(np.float32),
                "attention.dense.weight":
                    rng.standard_normal((hidden, hidden // n))
                    .astype(np.float32),
                "mlp.dense_h_to_4h.weight":
                    rng.standard_normal((4 * hidden // n, hidden))
                    .astype(np.float32),
                "input_layernorm.weight": np.ones(hidden, np.float32),
            }
            p = str(tmp_path / f"shard{i}.npz")
            np.savez(p, **sd)
            paths.append(p)
        return paths

    def test_identity_load(self, tmp_path):
        from deepspeed_tpu.runtime.state_dict_factory import MegatronSDLoader

        paths = self._make_shards(tmp_path)
        loader = MegatronSDLoader(paths, version=2.0)
        sd = loader.load(mp_world_size=2, mp_rank=1)
        assert sd["attention.dense.weight"].shape == (8, 4)

    def test_merge(self, tmp_path):
        from deepspeed_tpu.runtime.state_dict_factory import MegatronSDLoader

        paths = self._make_shards(tmp_path)
        loader = MegatronSDLoader(paths, version=2.0)
        sd = loader.load(mp_world_size=1, mp_rank=0)
        assert sd["attention.query_key_value.weight"].shape == (24, 8)
        assert sd["attention.dense.weight"].shape == (8, 8)
        assert sd["input_layernorm.weight"].shape == (8,)

    def test_split(self, tmp_path):
        from deepspeed_tpu.runtime.state_dict_factory import MegatronSDLoader

        paths = self._make_shards(tmp_path, n=1)
        loader = MegatronSDLoader(paths, version=2.0)
        sd0 = loader.load(mp_world_size=2, mp_rank=0)
        sd1 = loader.load(mp_world_size=2, mp_rank=1)
        assert sd0["attention.query_key_value.weight"].shape == (12, 8)
        assert sd0["mlp.dense_h_to_4h.weight"].shape == (16, 8)
        # merge of the splits reproduces the original
        loader_full = MegatronSDLoader(paths, version=2.0)
        full = loader_full.load(1, 0)
        merged = loader.merge_state_dicts([sd0, sd1])
        np.testing.assert_allclose(
            merged["attention.query_key_value.weight"],
            full["attention.query_key_value.weight"])

    def test_factory_json(self, tmp_path):
        from deepspeed_tpu.runtime.state_dict_factory import SDLoaderFactory

        paths = self._make_shards(tmp_path)
        loader = SDLoaderFactory.get_sd_loader_json(
            {"type": "Megatron", "checkpoints": paths, "version": 2.0})
        assert loader.ckpt_mp_size == 2


def test_env_report_runs():
    from deepspeed_tpu.env_report import debug_report, op_report

    rows = dict(debug_report())
    assert "jax" in rows
    ops = dict(op_report())
    assert "ds_cpu_adam" in ops
