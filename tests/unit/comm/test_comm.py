"""Comm facade tests — the analog of reference ``tests/unit/comm/test_dist.py``.

Covers the three planes of ``deepspeed_tpu.comm``:
* host-level (eager) collectives and the ``@timed_op`` accounting,
* in-compiled-code collectives (shard_map over the virtual 8-device mesh),
* the cross-rank consistency assertions (SURVEY §5.2 analog).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import comm as dist
from deepspeed_tpu.parallel import initialize_mesh


# ---------------------------------------------------------------------------
# host-plane collectives (single process: degenerate but exact semantics)
# ---------------------------------------------------------------------------
def test_all_reduce_host_ops():
    x = np.array([1.0, 2.0, 3.0])
    for op, expect in [
        (dist.ReduceOp.SUM, x), (dist.ReduceOp.AVG, x),
        (dist.ReduceOp.MIN, x), (dist.ReduceOp.MAX, x),
        (dist.ReduceOp.PRODUCT, x),
    ]:
        np.testing.assert_allclose(dist.all_reduce_host(x, op=op), expect)


def test_broadcast_and_allgather_host():
    x = np.arange(4, dtype=np.int32)
    np.testing.assert_array_equal(dist.broadcast_host(x, src=0), x)
    gathered = dist.all_gather_host(x)
    assert gathered.shape == (1, 4)  # world of one process
    np.testing.assert_array_equal(gathered[0], x)


def test_barrier_and_ranks():
    dist.barrier(name="test")  # no-op single process
    assert dist.get_rank() == 0
    assert dist.get_local_rank() == 0
    assert dist.get_world_size() == 1  # process count, not device count


def test_init_distributed_single_process():
    dist.init_distributed()
    assert dist.is_initialized()


# ---------------------------------------------------------------------------
# axis-name groups
# ---------------------------------------------------------------------------
def test_group_axes_and_sizes(eight_device_mesh):
    assert dist._axes("data") == ("data",)
    assert dist._axes(("data", "model")) == ("data", "model")
    assert dist._axes_size("data") == 8
    assert dist._axes_size(("data", "model")) == 8
    assert dist.get_world_size("data") == 8


def test_default_group_covers_zero_axes(eight_device_mesh):
    # default group = the ZeRO sharding axes (the reference's world group)
    axes = dist._axes(None)
    assert "data" in axes


# ---------------------------------------------------------------------------
# in-compiled-code collectives over the virtual mesh
# ---------------------------------------------------------------------------
@pytest.fixture
def shmap_mesh():
    return initialize_mesh(data=8)


def _shmap(mesh, fn, *args, in_specs, out_specs):
    from jax.experimental.shard_map import shard_map

    return jax.jit(shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs))(*args)


def test_all_reduce_in_jit(shmap_mesh):
    x = jnp.arange(8, dtype=jnp.float32)
    out = _shmap(shmap_mesh, lambda v: dist.all_reduce(v, group="data"),
                 x, in_specs=(P("data"),), out_specs=P("data"))
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))


def test_all_reduce_ops_in_jit(shmap_mesh):
    x = jnp.arange(8, dtype=jnp.float32)
    avg = _shmap(shmap_mesh, lambda v: dist.all_reduce(
        v, op=dist.ReduceOp.AVG, group="data"),
        x, in_specs=(P("data"),), out_specs=P("data"))
    np.testing.assert_allclose(np.asarray(avg), np.full(8, x.mean()))
    mx = _shmap(shmap_mesh, lambda v: dist.all_reduce(
        v, op=dist.ReduceOp.MAX, group="data"),
        x, in_specs=(P("data"),), out_specs=P("data"))
    np.testing.assert_allclose(np.asarray(mx), np.full(8, 7.0))


def test_all_gather_into_tensor_in_jit(shmap_mesh):
    x = jnp.arange(8, dtype=jnp.float32)
    out = _shmap(shmap_mesh,
                 lambda v: dist.all_gather_into_tensor(v, group="data"),
                 x, in_specs=(P("data"),), out_specs=P("data"))
    # every shard gathers the full vector; out_specs concatenates the copies
    np.testing.assert_allclose(np.asarray(out),
                               np.tile(np.arange(8, dtype=np.float32), 8))


def test_reduce_scatter_tensor_in_jit(shmap_mesh):
    # replicated ones on each rank → each rank's scattered slice sums to 8
    x = jnp.ones(8, jnp.float32)
    out = _shmap(shmap_mesh,
                 lambda v: dist.reduce_scatter_tensor(v, group="data"),
                 x, in_specs=(P(),), out_specs=P("data"))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 8.0))


def test_all_to_all_single_in_jit(shmap_mesh):
    # rank r holds row r; rank r sends chunk j to rank j and receives chunk r
    # from every rank, concatenated on axis 0 — a distributed transpose
    x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    out = _shmap(shmap_mesh,
                 lambda v: dist.all_to_all_single(
                     v, group="data", split_axis=1, concat_axis=0),
                 x, in_specs=(P("data"),), out_specs=P("data"))
    expect = np.arange(64, dtype=np.float32).reshape(8, 8).T.reshape(out.shape)
    np.testing.assert_allclose(np.asarray(out), expect)


def test_ppermute_ring_in_jit(shmap_mesh):
    x = jnp.arange(8, dtype=jnp.float32)
    perm = [(i, (i + 1) % 8) for i in range(8)]
    out = _shmap(shmap_mesh, lambda v: dist.ppermute(v, perm, group="data"),
                 x, in_specs=(P("data"),), out_specs=P("data"))
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8), 1))


def test_axis_index_in_jit(shmap_mesh):
    x = jnp.zeros(8, jnp.int32)
    out = _shmap(shmap_mesh,
                 lambda v: v + dist.axis_index(group="data"),
                 x, in_specs=(P("data"),), out_specs=P("data"))
    np.testing.assert_array_equal(np.asarray(out), np.arange(8))


def test_ppermute_rejects_multi_axis(shmap_mesh):
    with pytest.raises(ValueError):
        dist.ppermute(jnp.zeros(8), [(0, 1)], group=("data", "model"))


# ---------------------------------------------------------------------------
# timed_op accounting + traced-op records + log_summary
# ---------------------------------------------------------------------------
def test_timed_op_records_and_summary():
    dist.configure(enabled=True, prof_all=True, verbose=False)
    try:
        dist.all_reduce_host(np.ones(16, np.float32))
        dist.record_traced_op("all_gather_into_tensor", msg_size=1024, n_ranks=8)
        records = dist.comms_logger.comms_dict
        assert "all_reduce_host" in records
        assert "traced/all_gather_into_tensor" in records
        # record = msg-size keyed [count, [latencies], [algbw], [busbw]]
        size_entry = records["all_reduce_host"][16 * 4]
        assert size_entry[0] == 1
        summary = dist.log_summary()  # returns the records dict (via logger)
        assert "all_reduce_host" in summary
    finally:
        dist.configure(enabled=False, prof_all=False)
        dist.comms_logger.comms_dict.clear()


def test_timed_op_disabled_is_transparent():
    dist.configure(enabled=False)
    before = dict(dist.comms_logger.comms_dict)
    dist.all_reduce_host(np.ones(4))
    assert dist.comms_logger.comms_dict == before


# ---------------------------------------------------------------------------
# cross-rank consistency assertions (§5.2)
# ---------------------------------------------------------------------------
def test_stable_hash_deterministic_and_sensitive():
    a = {"input_ids": np.zeros((2, 8), np.int32)}
    b = {"input_ids": np.zeros((2, 8), np.int32)}
    c = {"input_ids": np.zeros((2, 9), np.int32)}
    assert dist.stable_hash(a) == dist.stable_hash(b)
    assert dist.stable_hash(a) != dist.stable_hash(c)
    assert dist.stable_hash({"x": 1, "y": 2}) == dist.stable_hash({"y": 2, "x": 1})


def test_assert_same_across_ranks_single_process():
    dist.assert_same_across_ranks({"anything": 1}, "noop")  # world of 1


def test_engine_consistency_flag_runs(eight_device_mesh):
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config(vocab_size=64, n_positions=16, n_embd=16, n_layer=1,
                     n_head=2, dtype=jnp.float32)
    eng, _, _, _ = ds.initialize(
        model=GPT2LMHeadModel(cfg),
        config={"train_micro_batch_size_per_gpu": 1,
                "check_rank_consistency": True,
                "zero_optimization": {"stage": 0},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    batch = {"input_ids": np.zeros((eng.train_batch_size(), 16), np.int32)}
    loss = float(eng.train_batch(batch=batch))
    assert np.isfinite(loss)
