"""Genuinely multi-process distributed tests (VERDICT r2 next #5).

Each test spawns N REAL localhost processes through ``common.run_distributed``
that rendezvous via ``init_distributed`` → ``jax.distributed.initialize``
(CPU/Gloo), then exercise collective + engine + checkpoint paths across the
process boundary. These fail if the rendezvous, the device federation, or
cross-process data movement breaks — the plane the virtual-mesh tests
cannot see (reference pattern: tests/unit/common.py:90 DistributedExec).
"""

import os
import sys
import tempfile

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import run_distributed  # noqa: E402

pytestmark = pytest.mark.filterwarnings("ignore")


# ---------------------------------------------------------------------------
# workers (module-level: imported by file path inside each spawned process)
# ---------------------------------------------------------------------------
def _collectives_worker(rank, world):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import deepspeed_tpu.comm.comm as dist

    assert jax.process_count() == world, jax.process_count()
    assert jax.device_count() == world  # one CPU device federated per proc

    # host-level collective plane
    dist.assert_same_across_ranks({"probe": 42}, "probe")

    # in-jit collective over the federated global mesh
    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")),
        np.full((2,), rank + 1.0, np.float32))
    total = float(jax.jit(lambda a: a.sum())(arr))
    expect = 2.0 * sum(range(1, world + 1))
    assert total == expect, (total, expect)

    # cross-rank divergence must be CAUGHT (the race/sanity plane)
    try:
        dist.assert_same_across_ranks({"divergent": rank}, "divergent")
    except RuntimeError:
        pass
    else:
        raise AssertionError("divergent value not detected across ranks")


def _engine_worker(rank, world):
    import jax
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )

    model = TransformerLM(TransformerConfig(
        vocab_size=64, n_embd=32, n_layer=2, n_head=4, max_seq_len=32))
    engine, _, _, _ = ds.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "zero_optimization": {"stage": 1},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "check_rank_consistency": True,
                "steps_per_print": 10 ** 9})
    assert engine.dp_world_size == world
    rng = np.random.default_rng(0)  # same data every rank (SPMD contract)
    losses = []
    for _ in range(4):
        batch = {"input_ids": rng.integers(
            0, 64, (2 * world, 32)).astype(np.int32)}
        losses.append(float(engine.train_batch(batch=batch)))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses

    # the loss is a global (replicated) value — every process must agree
    from deepspeed_tpu.comm import comm as dist
    dist.assert_same_across_ranks({"final_loss": round(losses[-1], 5)},
                                  "final loss")


def _checkpoint_worker(rank, world, ckpt_dir):
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )

    config = {"train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": 1,
              "zero_optimization": {"stage": 1},
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "bf16": {"enabled": True},
              "steps_per_print": 10 ** 9}

    def build():
        model = TransformerLM(TransformerConfig(
            vocab_size=64, n_embd=32, n_layer=2, n_head=4, max_seq_len=32))
        engine, _, _, _ = ds.initialize(model=model, config=dict(config))
        return engine

    rng = np.random.default_rng(1)
    batches = [{"input_ids": rng.integers(
        0, 64, (2 * world, 32)).astype(np.int32)} for _ in range(4)]

    engine = build()
    for b in batches[:2]:
        engine.train_batch(batch=b)
    engine.save_checkpoint(ckpt_dir, tag="mp")
    expected = [float(engine.train_batch(batch=b)) for b in batches[2:]]

    from deepspeed_tpu.parallel import mesh as mesh_mod
    mesh_mod.reset_mesh()
    resumed = build()
    resumed.train_batch(batch=batches[0])  # builds state (then overwritten)
    resumed.load_checkpoint(ckpt_dir, tag="mp")
    actual = [float(resumed.train_batch(batch=b)) for b in batches[2:]]
    np.testing.assert_allclose(actual, expected, rtol=1e-5)

    from deepspeed_tpu.comm import comm as dist
    dist.assert_same_across_ranks(
        {"resumed": [round(a, 5) for a in actual]}, "resumed losses")


# ---------------------------------------------------------------------------
# tests
# ---------------------------------------------------------------------------
def test_multiprocess_collectives():
    run_distributed(_collectives_worker, world_size=2)


def test_multiprocess_engine_train():
    run_distributed(_engine_worker, world_size=2)


def test_multiprocess_checkpoint_resume():
    with tempfile.TemporaryDirectory() as d:
        run_distributed(_checkpoint_worker, world_size=2, payload=d)


def _onebit_wire_worker(rank, world):
    """1-bit Adam with the compressed collective across REAL process
    boundaries: the int8 exchange must rendezvous and training must keep
    improving through the freeze boundary (VERDICT r2 #4 x #5)."""
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )

    model = TransformerLM(TransformerConfig(
        vocab_size=64, n_embd=32, n_layer=2, n_head=4, max_seq_len=32))
    engine, _, _, _ = ds.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "zero_optimization": {"stage": 0},
                "optimizer": {"type": "OneBitAdam",
                              "params": {"lr": 1e-3, "freeze_step": 2,
                                         "comm_backend_name": "compressed"}},
                "bf16": {"enabled": True},
                "steps_per_print": 10 ** 9})
    rng = np.random.default_rng(0)  # same data every rank (SPMD contract)
    batch = {"input_ids": rng.integers(0, 64, (2 * world * 2, 32)
                                       ).astype(np.int32)}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(5)]
    assert all(np.isfinite(losses)), losses
    # steps 3-5 run the compressed exchange (freeze_step=2); memorizing a
    # fixed batch must keep improving DURING the compressed phase — not
    # just end-vs-start, which the uncompressed warmup steps alone satisfy
    assert losses[-1] < losses[1], losses

    from deepspeed_tpu.comm import comm as dist
    dist.assert_same_across_ranks(
        {"wire_losses": [round(l, 5) for l in losses]}, "onebit wire losses")


def test_multiprocess_onebit_compressed_wire():
    run_distributed(_onebit_wire_worker, world_size=2)


def _param_offload_worker(rank, world):
    """offload_param streaming across REAL process boundaries (VERDICT r4
    next-#5): per-layer grads reduce across processes via their replicated
    out-sharding over the global mesh; every process's host Adam must stay
    in lockstep (identical losses AND identical streamed params)."""
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )

    model = TransformerLM(TransformerConfig(
        vocab_size=64, n_embd=32, n_layer=2, n_head=4, max_seq_len=32))
    engine, _, _, _ = ds.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "zero_optimization": {"offload_param": {"device": "cpu"}},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "steps_per_print": 10 ** 9})
    assert engine.dp_world_size == world
    rng = np.random.default_rng(0)  # same data every rank (SPMD contract)
    batch = {"input_ids": rng.integers(
        0, 64, (engine.train_batch_size(), 32)).astype(np.int32)}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(4)]
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses

    from deepspeed_tpu.comm import comm as dist
    dist.assert_same_across_ranks(
        {"po_losses": [round(l, 5) for l in losses]}, "offload losses")
    # the streamed param store itself must agree across processes (the
    # host Adam runs per-process on the reduced grads)
    import jax
    leaves = jax.tree_util.tree_leaves(
        engine._param_offload.store.stacked)
    digest = float(sum(float(np.abs(np.asarray(l, np.float32)).sum())
                       for l in leaves))
    dist.assert_same_across_ranks({"param_digest": round(digest, 4)},
                                  "streamed param digest")


def test_multiprocess_param_offload():
    run_distributed(_param_offload_worker, world_size=2)
