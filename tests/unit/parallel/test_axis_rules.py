"""Logical axis-rules table tests: shape-aware resolution must keep
re-partitioning recompile-free and bitwise-safe — size-1 mesh axes
normalize away (a TP=1 mesh resolves every rule to the replicated spec,
the tentpole's bitwise-parity-by-construction pin), indivisible dims
fall back to replicated (t5x), specs stay canonical (no trailing Nones,
no duplicate axes), and a typo'd mesh-axis name raises at table
construction instead of surfacing as a silent replicated placement."""

import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel import (DEFAULT_AXIS_RULES, LogicalAxisRules,
                                    cache_leaf_sharding, default_axis_rules,
                                    initialize_mesh, physical_spec,
                                    validate_axis_rules)


@pytest.fixture
def tp2_mesh(tp_mesh):
    return tp_mesh(data=4, model=2)


def test_validate_rejects_unknown_mesh_axis():
    with pytest.raises(ValueError, match="outside the declared universe"):
        validate_axis_rules((("heads", "modle"),))  # typo'd axis name
    with pytest.raises(ValueError, match="non-empty"):
        validate_axis_rules((("", "model"),))
    validate_axis_rules(DEFAULT_AXIS_RULES)  # the shipped table is clean


def test_tp1_mesh_resolves_everything_replicated(tp_mesh):
    """On a model=1 mesh every model-axis rule normalizes to the
    replicated spec — TP=1 placements are IDENTICAL to single-chip, so
    bitwise parity holds by construction, not by luck."""
    mesh = tp_mesh(data=8, model=1)
    rules = default_axis_rules()
    spec = rules.spec_for(("heads", "head_dim"), shape=(4, 8), mesh=mesh)
    assert spec == P()
    # the slots rule still engages: data=8 has size > 1
    assert rules.spec_for(("slots",), shape=(8,), mesh=mesh) == P("data")


def test_size1_axis_drops_and_spec_is_canonical(tp2_mesh):
    """Resolved specs must compare EQUAL to what GSPMD stamps on jit
    outputs: no trailing Nones, no size-1 axes, no duplicate axes —
    a textually-different-but-equivalent committed spec forks every
    donated-pool executable."""
    # trailing replicated dims are trimmed: P("model") not P("model", None)
    spec = physical_spec(("model", None), shape=(4, 8), mesh=tp2_mesh)
    assert spec == P("model")
    # a mesh axis used by an earlier dim is not repeated
    spec = physical_spec(("model", "model"), shape=(4, 4), mesh=tp2_mesh)
    assert spec == P("model")
    # axis absent from the mesh resolves replicated, not KeyError
    spec = physical_spec(("pipe", "model"), shape=(4, 4), mesh=tp2_mesh)
    assert spec == P(None, "model")


def test_divisibility_fallback(tp2_mesh):
    """A dim the mapped axis size does not divide replicates for THAT
    dim only (a 4-slot pool on a data=8 mesh keeps working)."""
    rules = default_axis_rules()
    # data=4 divides 8 slots -> sharded
    assert rules.spec_for(("slots",), shape=(8,), mesh=tp2_mesh) \
        == P("data")
    # data=4 does not divide 6 slots -> replicated
    assert rules.spec_for(("slots",), shape=(6,), mesh=tp2_mesh) == P()
    # model=2 divides heads=4 but not head_dim... other dims unaffected
    assert rules.spec_for(("heads", None), shape=(4, 7), mesh=tp2_mesh) \
        == P("model")


def test_first_match_wins_ordering():
    rules = LogicalAxisRules((("heads", "model"), ("heads", "data")))
    assert rules.mesh_axis("heads") == "model"
    assert rules.mesh_axis("unknown-name") is None
    assert rules.mesh_axis(None) is None


def test_shape_rank_mismatch_raises(tp2_mesh):
    with pytest.raises(ValueError, match="dims"):
        default_axis_rules().spec_for(("slots",), shape=(4, 4),
                                      mesh=tp2_mesh)


def test_cache_leaf_sharding_stacked_and_paged(tp2_mesh):
    """The pool seam resolves each serving-cache leaf's layout against
    its ACTUAL shape: slot rows shard over data, paged stores stay
    reachable from every data shard (pages replicated), head dims shard
    over model when divisible."""
    stacked = cache_leaf_sharding("stacked", mesh=tp2_mesh)
    # (layers, slots, kv_heads, head_dim, positions): slots 8 % data 4
    # == 0 and kv_heads 4 % model 2 == 0 -> both shard
    k = np.zeros((2, 8, 4, 8, 16), np.float32)
    sh = stacked("k", k)
    assert isinstance(sh, NamedSharding)
    assert sh.spec == P(None, "data", "model")
    # the slot index vector rides the data axis — the same placement
    # the engine commits its current-token twin to
    assert stacked("index", np.zeros((8,), np.int32)).spec == P("data")
    # unknown leaf key -> replicated, never a crash
    assert stacked("unknown", k).spec == P()

    paged = cache_leaf_sharding("paged", mesh=tp2_mesh)
    # pages dim replicated by rule; kv_heads still shards over model
    pk = np.zeros((2, 12, 4, 8, 16), np.float32)
    assert paged("k", pk).spec == P(None, None, "model")
    assert paged("table", np.zeros((8, 4), np.int32)).spec == P("data")


def test_mesh_default_resolution_uses_global(tp_mesh):
    """spec_for with no mesh argument resolves against the installed
    global mesh — the construction-time path the pools use."""
    tp_mesh(data=8, model=1)
    assert default_axis_rules().spec_for(("slots",), shape=(8,)) \
        == P("data")


def test_build_mesh_device_subsets():
    """Disjoint device subsets build disjoint meshes — the DP router's
    per-replica placement substrate."""
    import jax

    from deepspeed_tpu.parallel.mesh import build_mesh

    devs = jax.devices()
    m_a = build_mesh(devices=devs[:4], data=4, model=1)
    m_b = build_mesh(devices=devs[4:], data=4, model=1)
    assert set(m_a.devices.flat).isdisjoint(set(m_b.devices.flat))
    assert dict(m_a.shape)["data"] == 4
