"""Inference-stack tests (≅ reference tests/unit/inference/test_inference.py
model × dtype sweep, scaled to the unit harness):

- KV-cache decode logits == full-context recompute, per model family
- greedy generate with cache == naive argmax loop without cache
- AutoTP rule inference classifies col/row/embedding correctly
- TP generate produces identical tokens to single-replica generate
- sampling knobs (temperature/top_k/top_p) produce valid tokens
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import (
    FAMILY_PRESETS,
    TransformerLM,
    transformer_config,
)
from deepspeed_tpu.parallel import initialize_mesh

TINY = dict(vocab_size=64, max_seq_len=48, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)


def _model(family, **kw):
    cfg = transformer_config(family, **{**TINY, **kw})
    return TransformerLM(cfg), cfg


def _init(model, B=2, T=8, seed=0):
    ids = jax.random.randint(jax.random.PRNGKey(seed), (B, T), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    return params, ids


@pytest.mark.parametrize("family", sorted(FAMILY_PRESETS))
def test_kv_cache_decode_matches_recompute(family):
    kw = {"n_kv_head": 2} if family == "llama" else {}
    model, cfg = _model(family, **kw)
    params, ids = _init(model)

    # full-context logits (no cache)
    full = model.apply({"params": params}, ids, method=model.logits)

    # prefill on the first 5 tokens, then decode the rest one by one
    pre, vars_ = model.apply({"params": params}, ids[:, :5],
                             method=model.prefill, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(pre), np.asarray(full[:, :5]),
                               rtol=2e-4, atol=2e-4)
    cache = vars_["cache"]
    for t in range(5, ids.shape[1]):
        step, vars_ = model.apply(
            {"params": params, "cache": cache}, ids[:, t:t + 1],
            jnp.asarray(t, jnp.int32), method=model.decode, mutable=["cache"])
        cache = vars_["cache"]
        np.testing.assert_allclose(np.asarray(step[:, 0]),
                                   np.asarray(full[:, t]),
                                   rtol=2e-4, atol=2e-4, err_msg=f"pos {t}")


def test_generate_greedy_matches_naive():
    model, cfg = _model("gpt2")
    params, ids = _init(model, B=2, T=6)
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    out = engine.generate(ids, max_new_tokens=6)
    assert out.shape == (2, 12)

    # naive: recompute full logits each step, take argmax
    cur = np.asarray(ids)
    for _ in range(6):
        logits = model.apply({"params": params}, jnp.asarray(cur),
                             method=model.logits)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))[:, None]
        cur = np.concatenate([cur, nxt], axis=1)
    np.testing.assert_array_equal(out, cur)


def test_generate_sampling_and_eos():
    model, cfg = _model("gpt2")
    params, ids = _init(model, B=2, T=4)
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    out = engine.generate(ids, max_new_tokens=8, do_sample=True,
                          temperature=0.8, top_k=10, top_p=0.9, seed=3)
    assert out.shape == (2, 12)
    assert (out >= 0).all() and (out < 64).all()
    # eos early-exit: force eos to the first greedily-produced token
    g = engine.generate(ids, max_new_tokens=4)
    eos = int(g[0, 4])
    out2 = engine.generate(ids[:1], max_new_tokens=8, eos_token_id=eos)
    assert out2.shape[1] <= 12


def test_auto_tp_rules_classification():
    from deepspeed_tpu.module_inject import auto_tp_rules

    model, cfg = _model("llama")
    params, _ = _init(model)
    rules = auto_tp_rules(params, tp_size=2)
    spec = rules.spec_for("blocks/block/attn/q_proj/kernel")
    assert spec is not None and spec[-1] == "model"          # column
    spec = rules.spec_for("blocks/block/attn/o_proj/kernel")
    assert spec is not None and spec[-2] == "model"          # row
    spec = rules.spec_for("embed_tokens/embedding")
    assert spec is not None and spec[-2] == "model"          # vocab-parallel
    spec = rules.spec_for("blocks/block/mlp/down_proj/kernel")
    assert spec is not None and spec[-2] == "model"          # row


def test_tp_generate_matches_single_replica():
    from deepspeed_tpu.parallel import reset_mesh

    model, cfg = _model("llama")
    params, ids = _init(model, B=2, T=5)
    # true single-replica reference: pure data mesh, tp=1
    ref_mesh = initialize_mesh(data=8)
    ref_engine = ds.init_inference(model=model, model_parameters=params,
                                   config={"dtype": "float32"}, mesh=ref_mesh)
    assert ref_engine.mp_world_size == 1
    want = ref_engine.generate(ids, max_new_tokens=5)

    reset_mesh()
    tp_mesh = initialize_mesh(data=1, model=8)
    tp_engine = ds.init_inference(model=model, model_parameters=params,
                                  config={"dtype": "float32", "mp_size": 8},
                                  mesh=tp_mesh)
    assert tp_engine.mp_world_size == 8
    got = tp_engine.generate(ids, max_new_tokens=5)
    np.testing.assert_array_equal(got, want)


def test_transformer_lm_trains_with_engine():
    """The unified model doubles as a training model (engine convention)."""
    model, cfg = _model("llama", remat=True)
    engine, _, _, _ = ds.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "zero_optimization": {"stage": 2}})
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, 64, (engine.train_batch_size(), 16)).astype(np.int32)}
    l0 = float(engine.train_batch(batch=batch))
    for _ in range(4):
        ln = float(engine.train_batch(batch=batch))
    assert np.isfinite(ln) and ln < l0
