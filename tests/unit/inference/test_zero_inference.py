"""ZeRO-Inference weight streaming — analog of the reference's
ZeRO-inference checkpoint-streaming tests (test_checkpoint_sharding /
zero-inference paths): streamed logits must equal the all-on-device
forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.zero_inference import ZeroInferenceEngine
from deepspeed_tpu.models.transformer_lm import (
    TransformerConfig,
    TransformerLM,
    transformer_config,
)


def _model_and_params(family="gpt2", n_layer=3):
    cfg = transformer_config(family, vocab_size=64, n_layer=n_layer,
                             n_head=2, n_embd=32, max_seq_len=32,
                             dtype=jnp.float32)
    model = TransformerLM(cfg)
    ids = jnp.ones((1, 8), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, ids,
                        method=model.logits)["params"]
    return cfg, model, params


def test_streamed_matches_resident():
    cfg, model, params = _model_and_params()
    ids = jnp.asarray(np.random.default_rng(0)
                      .integers(0, 64, (2, 16)).astype(np.int32))
    ref = model.apply({"params": params}, ids, method=model.logits)

    host = jax.device_get(params)
    zi = ZeroInferenceEngine(cfg, host, dtype=jnp.float32)
    out = zi(ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_streamed_bloom_family():
    """bloom has an embedding layernorm — the streamed path must apply
    it (regression for a dropped embed_ln)."""
    cfg, model, params = _model_and_params(family="bloom")
    ids = jnp.asarray(np.random.default_rng(2)
                      .integers(0, 64, (2, 12)).astype(np.int32))
    ref = model.apply({"params": params}, ids, method=model.logits)
    zi = ZeroInferenceEngine(cfg, jax.device_get(params), dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(zi(ids)), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_streamed_llama_family():
    cfg, model, params = _model_and_params(family="llama")
    ids = jnp.asarray(np.random.default_rng(1)
                      .integers(0, 64, (2, 12)).astype(np.int32))
    ref = model.apply({"params": params}, ids, method=model.logits)
    zi = ZeroInferenceEngine(cfg, jax.device_get(params), dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(zi(ids)), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_prefetch_variants_agree():
    cfg, model, params = _model_and_params(n_layer=4)
    ids = jnp.ones((1, 8), jnp.int32)
    host = jax.device_get(params)
    outs = [np.asarray(ZeroInferenceEngine(cfg, host, dtype=jnp.float32,
                                           prefetch=p)(ids))
            for p in (0, 1, 3)]
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6)
    np.testing.assert_allclose(outs[0], outs[2], rtol=1e-6)


def test_score_ranks_likely_sequences():
    cfg, model, params = _model_and_params()
    zi = ZeroInferenceEngine(cfg, jax.device_get(params), dtype=jnp.float32)
    ids = np.random.default_rng(0).integers(0, 64, (3, 16)).astype(np.int32)
    scores = zi.score(ids)
    assert scores.shape == (3,)
    assert np.isfinite(scores).all()


def test_memmap_host_weights(tmp_path):
    """Weights can live in a memory-mapped file (the NVMe tier)."""
    cfg, model, params = _model_and_params()
    host = jax.device_get(params)
    # dump the stacked block weights to disk, reload as memmaps
    import pickle

    flat, tree = jax.tree_util.tree_flatten(host)
    paths = []
    for i, leaf in enumerate(flat):
        p = tmp_path / f"w{i}.npy"
        np.save(p, np.asarray(leaf))
        paths.append(p)
    mapped = jax.tree_util.tree_unflatten(
        tree, [np.load(p, mmap_mode="r") for p in paths])
    ids = jnp.ones((1, 8), jnp.int32)
    ref = model.apply({"params": host}, ids, method=model.logits)
    out = ZeroInferenceEngine(cfg, mapped, dtype=jnp.float32)(ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_streamed_generate_matches_resident():
    """generate() under weight streaming (per-token layer restream, KV
    caches device-resident) must produce the same greedy tokens as the
    all-on-device engine's generate — the ZeRO-Inference serving mode
    (reference docs/_posts/2022-09-10-zero-inference.md)."""
    import deepspeed_tpu as ds

    cfg, model, params = _model_and_params(family="llama")
    ids = jnp.asarray(np.random.default_rng(5)
                      .integers(0, 64, (2, 6)).astype(np.int32))

    resident = ds.init_inference(model, model_parameters=params,
                                 dtype="float32")
    expect = resident.generate(ids, max_new_tokens=6)

    zi = ZeroInferenceEngine(cfg, jax.device_get(params), dtype=jnp.float32)
    got = zi.generate(ids, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expect))


def test_streamed_generate_contracts():
    """Engine-dtype != config-dtype must still generate (cache dtype is
    the module's, not the engine's), and max_new_tokens=0 returns the
    prompt — both matching the resident engine's contracts."""
    cfg, model, params = _model_and_params()
    ids = jnp.asarray(np.random.default_rng(6)
                      .integers(0, 64, (2, 5)).astype(np.int32))
    zi = ZeroInferenceEngine(cfg, jax.device_get(params),
                             dtype=jnp.bfloat16)  # cfg is float32
    out = zi.generate(ids, max_new_tokens=3)
    assert out.shape == (2, 8) and (out[:, :5] == np.asarray(ids)).all()
    np.testing.assert_array_equal(zi.generate(ids, max_new_tokens=0),
                                  np.asarray(ids))


def test_int8_streaming_tier():
    """int8=True quantizes the streamed Dense kernels to the QuantDense
    layout: each layer ships ~half the bytes, logits track the bf16
    stream, and generation still works (int8 ZeRO-Inference — the
    streamed analog of the engine's dtype=int8 tier)."""
    cfg, model, params = _model_and_params(family="llama", n_layer=3)
    host = jax.device_get(params)
    ids = jnp.asarray(np.random.default_rng(7)
                      .integers(0, 64, (2, 10)).astype(np.int32))

    ref_eng = ZeroInferenceEngine(cfg, host, dtype=jnp.float32)
    q_eng = ZeroInferenceEngine(cfg, host, dtype=jnp.float32, int8=True)

    # per-layer wire bytes drop close to half (scales/norms keep f32)
    assert sum(q_eng._leaf_nbytes) < 0.7 * sum(ref_eng._leaf_nbytes)

    ref = np.asarray(ref_eng(ids), np.float32)
    got = np.asarray(q_eng(ids), np.float32)
    agree = (ref.argmax(-1) == got.argmax(-1)).mean()
    assert agree > 0.9, agree

    toks = q_eng.generate(ids, max_new_tokens=4)
    assert toks.shape == (2, 14)
