"""graftcheck signature machinery: the static signature grammar must be
byte-identical to the runtime warmup-manifest grammar, the abstract
interpreter must enumerate the serving stack's reachable signature set
finitely, and a manifest divergence in EITHER direction must fail.

Includes the CLI subprocess tier: `bin/graftlint --check` (exit 0 on
the repo), `--check --manifest` (exit 1 on seeded divergence), and
`--inventory --signatures` (reproducible static manifest, no jax)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.analysis.absdomain import (HOST, Arr, FiniteSet,
                                              IntRange, Known, Scalar,
                                              SignatureError, Tree, Tup,
                                              Unbounded, Unknown,
                                              expand_signatures)
from deepspeed_tpu.analysis.interp import (default_check_envs,
                                           diff_manifest, enumerate_union)
from deepspeed_tpu.telemetry.watchdog import manifest_signature

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    deepspeed_tpu.__file__)))
GRAFTLINT = os.path.join(REPO, "bin", "graftlint")


# ---------------------------------------------- grammar round-trip
def test_static_grammar_matches_runtime_grammar():
    """One call rendered by both halves must agree byte-for-byte."""
    runtime = manifest_signature(
        (np.zeros((8, 1), np.int32), np.ones((8,), np.int32),
         {"cache": None}, 0, 1.0, True),
        {"rows": np.zeros((2, 16), np.int32)})
    static = expand_signatures(
        [Arr((Known(8), Known(1)), "int32", HOST),
         Arr((Known(8),), "int32", HOST),
         Tree(HOST, "cache"), Scalar(0), Scalar(1.0), Scalar(True)],
        {"rows": Arr((Known(2), Known(16)), "int32", HOST)})
    assert static == [runtime]


def test_runtime_grammar_containers_and_scalars():
    assert manifest_signature(({"a": 1}, [1, 2], (3,)), {}) == "(*, *, *)"
    assert manifest_signature((1, 2.5, None, "x"), {}) == \
        "(1, 2.5, None, 'x')"
    assert manifest_signature((), {"b": 2, "a": 1}) == "(a=1, b=2)"


def test_expand_joint_dims_by_identity():
    # the SAME FiniteSet object in two shapes expands JOINTLY ...
    b = FiniteSet([1, 2], "B")
    sigs = expand_signatures([Arr((b, Known(1)), "float32", HOST),
                              Arr((b,), "int32", HOST)])
    assert sigs == ["(float32[1,1], int32[1])", "(float32[2,1], int32[2])"]
    # ... while two DISTINCT sets expand as a cartesian product
    sigs2 = expand_signatures(
        [Arr((FiniteSet([1, 2]), Known(1)), "float32", HOST),
         Arr((FiniteSet([1, 2]),), "int32", HOST)])
    assert len(sigs2) == 4


def test_expand_failure_modes():
    with pytest.raises(SignatureError) as e:
        expand_signatures([Arr((Unbounded("n"),), "int32", HOST)])
    assert e.value.kind == "unbounded-signature"
    with pytest.raises(SignatureError) as e2:
        expand_signatures([Unknown("host readback")])
    assert e2.value.kind == "signature-escape"
    with pytest.raises(SignatureError) as e3:
        expand_signatures([Arr((IntRange(1, 1000),), "f32", HOST),
                           Arr((IntRange(1, 1000),), "f32", HOST)])
    assert e3.value.kind == "unbounded-signature"  # product over the cap
    with pytest.raises(SignatureError) as e4:
        expand_signatures([Tup([Scalar(1)])])
    assert e4.value.kind == "signature-escape"


# ------------------------------------------- whole-stack enumeration
def test_default_envs_enumerate_finitely():
    res = enumerate_union(default_check_envs(), REPO)
    assert res.findings == []
    progs = res.programs
    # every watched program family shows up
    for name in ("InferenceEngine._jit_prefill_at",
                 "InferenceEngine._jit_decode",
                 "InferenceEngine._jit_prefill_chunk",
                 "InferenceEngine._jit_sample",
                 "SlotPool._admit_jit", "SlotPool._admit_rows_jit",
                 "SlotPool._paged_decode_jit", "SlotPool._jit_copy_page",
                 "SlotPool._paged_chunk_jit"):
        assert progs.get(name), f"missing program {name}"
    # the stall-free row's admission set: singleton width buckets
    # 16..256 plus every (rows x width) group the 1024-token budget
    # allows — 19 exactly (the hand-derived count the bench sweeps)
    pre = [s for s in progs["InferenceEngine._jit_prefill_at"]
           if "int32[1," in s]
    assert any("int32[1,16]" in s for s in pre)
    assert any("int32[1,1024]" in s for s in pre)  # serial arm bucket
    rows = progs["SlotPool._admit_rows_jit"]
    # dense 4-arg form: 8 shorts coalesce into one bucketed admit
    assert "(*, *, int32[8], int32[8])" in rows
    # paged 5-arg form carries the per-row page tables (pages_per_slot=8)
    assert "(*, *, int32[4,8], int32[4], int32[4])" in rows
    assert not any("int32[16]" in s for s in rows)  # capped at slots


def test_enumeration_is_deterministic():
    a = enumerate_union(default_check_envs(), REPO).programs
    b = enumerate_union(default_check_envs(), REPO).programs
    assert a == b


# ------------------------------------------------- manifest diffing
def _static_doc():
    envs = default_check_envs()
    res = enumerate_union(envs, REPO)
    return {"version": 1, "configs": envs,
            "programs": {k: sorted(v) for k, v in res.programs.items()}}


def test_manifest_diff_both_directions():
    doc = _static_doc()
    assert diff_manifest(doc["programs"], doc["programs"]) == []
    # static-only signature: the warmup sweep never traced it -> it
    # WILL compile post-warmup
    lean = {k: list(v) for k, v in doc["programs"].items()}
    dropped = lean["InferenceEngine._jit_decode"].pop()
    diffs = diff_manifest(doc["programs"], lean)
    assert len(diffs) == 1 and dropped in diffs[0]
    assert "never" in diffs[0] or "post-warmup" in diffs[0]
    # runtime-only signature: the static enumeration lost coverage
    fat = {k: list(v) for k, v in doc["programs"].items()}
    fat["InferenceEngine._jit_decode"] = \
        fat["InferenceEngine._jit_decode"] + ["(int32[99,99])"]
    diffs2 = diff_manifest(doc["programs"], fat)
    assert len(diffs2) == 1 and "(int32[99,99])" in diffs2[0]
    assert "missed" in diffs2[0]
    # an extra runtime-only PROGRAM is a divergence too
    extra = dict(doc["programs"])
    extra["Ghost._jit"] = ["(int32[1])"]
    assert diff_manifest(doc["programs"], extra)


# ------------------------------------------------------ CLI subprocess
def _run(args, **kw):
    return subprocess.run([sys.executable, GRAFTLINT] + args,
                          capture_output=True, text=True, timeout=120,
                          cwd=str(REPO), **kw)


def test_cli_check_manifest_match_and_divergence(tmp_path):
    doc = _static_doc()
    good = tmp_path / "signatures.json"
    good.write_text(json.dumps(doc))
    proc = _run(["--check", "--manifest", str(good)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "matches" in proc.stdout

    doc["programs"]["InferenceEngine._jit_decode"] = \
        doc["programs"]["InferenceEngine._jit_decode"][:-1]
    bad = tmp_path / "diverged.json"
    bad.write_text(json.dumps(doc))
    proc2 = _run(["--check", "--manifest", str(bad)])
    assert proc2.returncode == 1
    assert "divergence" in proc2.stdout

    notman = tmp_path / "not_a_manifest.json"
    notman.write_text("{\"hello\": 1}")
    assert _run(["--check", "--manifest", str(notman)]).returncode == 2


def test_cli_inventory_signatures_reproducible(tmp_path):
    out = tmp_path / "static.json"
    proc = _run(["--inventory", "--signatures", str(out)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    assert doc["version"] == 1
    assert doc["programs"] == _static_doc()["programs"]
    # bare --signatures prints the same document to stdout
    proc2 = _run(["--inventory", "--signatures"])
    assert proc2.returncode == 0
    assert json.loads(proc2.stdout)["programs"] == doc["programs"]


def test_sharded_env_enumerates_identically_to_dense():
    """The serving-tp sharded config (mesh_data=4, mesh_model=2) must
    enumerate the EXACT signature set of its dense twin: a (data, model)
    mesh moves array placements, never traced shapes — the recompile-
    free tentpole invariant, pinned at the static-analysis layer. A
    divergence here means a mesh knob leaked into a traced shape."""
    envs = default_check_envs()
    sharded = [e for e in envs if e.get("mesh_model", 1) > 1]
    assert sharded, "default_check_envs lost the serving-tp sharded env"
    (sharded_env,) = sharded
    dense_env = {k: v for k, v in sharded_env.items()
                 if k not in ("mesh_data", "mesh_model")}
    assert dense_env in envs  # the dense twin ships in the same set
    a = enumerate_union([dense_env], REPO)
    b = enumerate_union([sharded_env], REPO)
    assert a.findings == [] and b.findings == []
    assert a.programs == b.programs
