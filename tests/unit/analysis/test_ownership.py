"""graftown fixtures and drift tests.

Every ownership rule must FIRE on its seeded violation and stay SILENT
on the paired known-false-positive shape (release in ``finally``,
conditional acquire matched by the same-condition release,
snapshot-then-restore rollback, refcount handoff to the prefix trie as
an ownership transfer).  The effect table and the inferred summaries
are then pinned in both directions, like ``test_concurrency.py`` pins
the thread-context map: dropping a primitive from the table and adding
a new lifecycle helper both show up as a diff, and every runtime
``check_invariants``/``consistency_errors`` sweep must be claimed by a
static resource kind (and vice versa).
"""

import ast
import json
import os
import subprocess
import sys
import time

import deepspeed_tpu
from deepspeed_tpu.analysis import (EFFECT_TABLE, OWN_RULE_IDS, OWN_RULES,
                                    RUNTIME_AUDIT, EffectMap,
                                    analyze_source, effect_inventory,
                                    effect_table_dict, iter_python_files)
from deepspeed_tpu.analysis.dataflow import ModuleIndex

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    deepspeed_tpu.__file__)))
SERVING = os.path.join(REPO, "deepspeed_tpu", "serving")
GRAFTLINT = os.path.join(REPO, "bin", "graftlint")


def _errors(src, rule=None):
    out = [f for f in analyze_source(src, rules=OWN_RULES)
           if f.severity == "error" and not f.suppressed]
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


# ------------------------------------------ leak-on-exception-path
def test_leak_on_exception_path_fires():
    src = (
        "class E:\n"
        "    def admit(self, pool, req):\n"
        "        slot = pool.alloc()\n"
        "        pool.reset_row(slot)\n"
        "        req.slot = slot\n")
    (f,) = _errors(src, "leak-on-exception-path")
    assert f.line == 3 and "slot" in f.message and "4" in f.message


def test_release_on_exception_edge_stays_silent():
    src = (
        "class E:\n"
        "    def admit(self, pool, req):\n"
        "        slot = pool.alloc()\n"
        "        try:\n"
        "            pool.reset_row(slot)\n"
        "        except Exception:\n"
        "            pool.release(slot)\n"
        "            raise\n"
        "        req.slot = slot\n")
    assert not _errors(src)


def test_release_in_finally_stays_silent():
    src = (
        "class E:\n"
        "    def locked(self):\n"
        "        self._lock.acquire()\n"
        "        try:\n"
        "            self.work()\n"
        "        finally:\n"
        "            self._lock.release()\n")
    assert not _errors(src)


# ------------------------------- cross-pool page transfer primitive
def test_transfer_import_leaks_when_seating_raises():
    # import_pages hands back an OWNED batch; seat_pages can raise, so
    # a bare import->seat with no unwind leaks the batch on that edge
    src = (
        "class E:\n"
        "    def adopt(self, pool, spool, slot, pages, pos):\n"
        "        dst = pool.import_pages(spool, pages)\n"
        "        pool.seat_pages(slot, dst, pos)\n")
    (f,) = _errors(src, "leak-on-exception-path")
    assert f.line == 3 and "page" in f.message and "4" in f.message


def test_transfer_unref_batch_on_seat_failure_stays_silent():
    # the real adopt() shape: seat_pages is atomic, so its failure
    # hands the WHOLE batch back via the bulk unref — owned-until-
    # seated, then ownership transfers into the slot table
    src = (
        "class E:\n"
        "    def adopt(self, pool, spool, slot, pages, pos):\n"
        "        dst = pool.import_pages(spool, pages)\n"
        "        try:\n"
        "            pool.seat_pages(slot, dst, pos)\n"
        "        except Exception:\n"
        "            pool.unref_pages(dst)\n"
        "            raise\n")
    assert not _errors(src)


def test_transfer_source_page_double_unref_fires():
    # the source side of a transfer drops its reference exactly once;
    # a second unref on the same handle is a double-release
    src = (
        "class E:\n"
        "    def hand_off(self, pool):\n"
        "        pid = pool.alloc_page()\n"
        "        pool.unref_page(pid)\n"
        "        pool.unref_page(pid)\n")
    (f,) = _errors(src, "double-release")
    assert f.line == 5


# ------------------------------------------------- double-release
def test_double_release_fires():
    src = (
        "class E:\n"
        "    def f(self, pool, slot):\n"
        "        pool.release(slot)\n"
        "        pool.release(slot)\n")
    (f,) = _errors(src, "double-release")
    assert f.line == 4


def test_conditional_acquire_same_condition_release_stays_silent():
    # the condition-memoisation FP shape: both guards share one test,
    # so only the (taken, taken) and (skipped, skipped) paths exist
    src = (
        "class E:\n"
        "    def f(self, pool, pid, need):\n"
        "        if need:\n"
        "            pool.ref_page(pid)\n"
        "        self.ticks = self.ticks + 1\n"
        "        if need:\n"
        "            pool.unref_page(pid)\n")
    assert not _errors(src)


# ---------------------------------------------- use-after-release
def test_use_after_release_fires():
    src = (
        "class E:\n"
        "    def f(self, pool, slot):\n"
        "        pool.release(slot)\n"
        "        pool.advance(slot)\n")
    (f,) = _errors(src, "use-after-release")
    assert f.line == 4


def test_realloc_and_seat_after_release_stays_silent():
    src = (
        "class E:\n"
        "    def f(self, pool, req, slot):\n"
        "        pool.release(slot)\n"
        "        slot = pool.alloc()\n"
        "        req.slot = slot\n"
        "        pool.advance(slot)\n")
    assert not _errors(src)


# --------------------------------------------- unbalanced-refcount
def test_unbalanced_refcount_fires():
    src = (
        "class E:\n"
        "    def f(self, pool, pid):\n"
        "        pool.ref_page(pid)\n"
        "        self.hits = self.hits + 1\n")
    (f,) = _errors(src, "unbalanced-refcount")
    assert f.line == 3


def test_returned_ref_counts_as_handoff():
    # returning the page id hands the ref to the caller — the static
    # form of `alloc_page` itself, whose caller owes the unref
    src = (
        "class E:\n"
        "    def f(self, pool, pid):\n"
        "        pool.ref_page(pid)\n"
        "        return pid\n")
    assert not _errors(src)


def test_trie_handoff_counts_as_ownership_transfer():
    # refcount handed to the prefix trie: `insert` is a transfer
    # primitive, so the ref is balanced by the handoff, not an unref
    src = (
        "class E:\n"
        "    def f(self, pool, trie, pid, key):\n"
        "        pool.ref_page(pid)\n"
        "        trie.insert(key, pid)\n")
    assert not _errors(src)


# ------------------------------------------------ missing-rollback
def test_missing_rollback_fires():
    src = (
        "class E:\n"
        "    def admit(self, req):\n"
        "        try:\n"
        "            req.state = 'PREFILLING'\n"
        "            self.pool.admit(req.slot)\n"
        "        except Exception:\n"
        "            self.log()\n"
        "            raise\n")
    (f,) = _errors(src, "missing-rollback")
    assert "state" in f.message


def test_snapshot_then_restore_stays_silent():
    src = (
        "class E:\n"
        "    def admit(self, req):\n"
        "        old = req.state\n"
        "        try:\n"
        "            req.state = 'PREFILLING'\n"
        "            self.pool.admit(req.slot)\n"
        "        except Exception:\n"
        "            req.state = old\n"
        "            raise\n")
    assert not _errors(src)


def test_own_rule_ids_are_pragma_addressable():
    # a reasoned pragma must suppress each own rule (the triage
    # workflow depends on it)
    src = (
        "class E:\n"
        "    def admit(self, pool, req):\n"
        "        slot = pool.alloc()  # graftlint: "
        "allow[leak-on-exception-path] -- fixture: deliberate\n"
        "        pool.reset_row(slot)\n"
        "        req.slot = slot\n")
    out = analyze_source(src, rules=OWN_RULES)
    assert [f.rule for f in out if f.suppressed] == \
        ["leak-on-exception-path"]
    assert not [f for f in out if f.counts_as_error]
    assert OWN_RULE_IDS == {r.id for r in OWN_RULES}


# -------------------------------------------------- effects drift
def test_effect_table_pins_every_primitive():
    """Direction one of the drift test: dropping a primitive from the
    table (or a whole kind) breaks this golden pin."""
    assert effect_table_dict() == {
        "future": {"acquire": ["create_future"],
                   "release": ["set_exception", "set_result"]},
        "lock": {"acquire": ["acquire"], "release": ["release"]},
        "page": {"acquire": ["alloc_page", "import_pages"],
                 "ref": ["ref_page"],
                 "transfer": ["insert", "map_prefix", "seat_pages",
                              "seat_prefix"],
                 "unref": ["unref_page", "unref_pages"]},
        "seat": {"acquire": ["grant"],
                 "release": ["expire", "requeue_back", "requeue_front"],
                 "use": ["submit"]},
        "slot": {"acquire": ["alloc"], "release": ["release"],
                 "release_all": ["reset"],
                 "use": ["admit", "admit_rows", "advance",
                         "cache_prefix", "ensure_writable",
                         "map_prefix", "reset_row", "run_prefill_chunk",
                         "seat_prefix"]},
    }


def test_new_lifecycle_helper_shows_up_in_effects():
    """Direction two: a new helper that releases through a table
    primitive is inferred (and propagates to its callers) without any
    table change."""
    src = (
        "class P:\n"
        "    def scrub(self, slot):\n"
        "        self.pool.release(slot)\n"
        "    def outer(self, req):\n"
        "        self.scrub(req.slot)\n")
    labels = EffectMap(ModuleIndex(ast.parse(src))).labels()
    assert labels["P.scrub"]["releases"] == ["arg1"]
    assert labels["P.outer"]["releases"] == ["arg1.slot"]


def test_effects_inventory_matches_cli_dump():
    inv = effect_inventory([SERVING])
    assert inv["table"] == effect_table_dict()
    by_base = {os.path.basename(k): v for k, v in inv["files"].items()}
    # the eviction helper is the canonical transitive release: every
    # caller of _evict_slot inherits `releases req.slot`
    assert by_base["engine.py"]["ServingEngine._evict_slot"][
        "releases"] == ["arg1.slot"]
    proc1 = subprocess.run(
        [sys.executable, GRAFTLINT, "--effects",
         os.path.join("deepspeed_tpu", "serving")],
        capture_output=True, text=True, timeout=120, cwd=str(REPO))
    assert proc1.returncode == 0, proc1.stdout + proc1.stderr
    doc = json.loads(proc1.stdout)
    assert doc["version"] == 1
    assert doc["table"] == inv["table"]
    cli_by_base = {os.path.basename(k): v
                   for k, v in doc["files"].items()}
    assert cli_by_base == by_base
    # reproducible: a second run emits byte-identical JSON
    proc2 = subprocess.run(
        [sys.executable, GRAFTLINT, "--effects",
         os.path.join("deepspeed_tpu", "serving")],
        capture_output=True, text=True, timeout=120, cwd=str(REPO))
    assert proc2.stdout == proc1.stdout


# ------------------------------------- runtime-audit cross-reference
def _serving_class_methods():
    methods = set()
    for fp in iter_python_files([SERVING]):
        with open(fp, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        methods.add(f"{node.name}.{sub.name}")
    return methods


def test_runtime_audit_cross_reference_both_directions():
    """Every effect-table kind names its runtime sweep, every named
    sweep exists in serving/, and every runtime
    ``check_invariants``/``consistency_errors`` definition is claimed
    by some kind — a new pool resource cannot skip the static tier."""
    assert set(RUNTIME_AUDIT) == set(EFFECT_TABLE)
    methods = _serving_class_methods()
    for kind, audits in RUNTIME_AUDIT.items():
        for qual in audits:
            assert qual in methods, (
                f"RUNTIME_AUDIT[{kind!r}] names {qual} but serving/ "
                "has no such method")
    claimed = {q for quals in RUNTIME_AUDIT.values() for q in quals}
    sweeps = {m for m in methods
              if m.split(".")[1] in ("check_invariants",
                                     "consistency_errors")}
    assert sweeps <= claimed, (
        f"runtime sweeps unclaimed by any static kind: "
        f"{sorted(sweeps - claimed)}")


# ------------------------------------------------ CLI tier budget
def test_own_cli_under_two_seconds_without_jax():
    """`bin/graftlint --tier own` over the gated surface: exit 0 with
    NO baseline, < 2 s, and the standalone loader must never pull in
    jax."""
    surface = [os.path.join("deepspeed_tpu", "serving")]
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, GRAFTLINT, "--tier", "own"] + surface,
        capture_output=True, text=True, timeout=60, cwd=str(REPO))
    wall = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert wall < 2.0, f"--tier own took {wall:.2f}s (budget 2s)"
    probe = subprocess.run(
        [sys.executable, "-c",
         "import runpy, sys\n"
         "sys.argv = ['graftlint', '--tier', 'own'] + %r\n"
         "try:\n"
         "    runpy.run_path(%r, run_name='__main__')\n"
         "except SystemExit as e:\n"
         "    assert e.code == 0, e.code\n"
         "assert 'jax' not in sys.modules, 'graftlint imported jax'\n"
         % (surface, GRAFTLINT)],
        capture_output=True, text=True, timeout=60, cwd=str(REPO))
    assert probe.returncode == 0, probe.stdout + probe.stderr


def test_own_cli_fails_on_seeded_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("class E:\n"
                   "    def admit(self, pool, req):\n"
                   "        slot = pool.alloc()\n"
                   "        pool.reset_row(slot)\n"
                   "        req.slot = slot\n")
    proc = subprocess.run(
        [sys.executable, GRAFTLINT, "--tier", "own", str(bad)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "leak-on-exception-path" in proc.stdout
    # the default all-tiers run catches it too
    proc2 = subprocess.run(
        [sys.executable, GRAFTLINT, str(bad)],
        capture_output=True, text=True, timeout=60)
    assert proc2.returncode == 1
    assert "leak-on-exception-path" in proc2.stdout
    # bad path -> usage error, distinct from gate failure
    proc3 = subprocess.run(
        [sys.executable, GRAFTLINT, "--tier", "own",
         str(tmp_path / "missing.py")],
        capture_output=True, text=True, timeout=60)
    assert proc3.returncode == 2
