"""Fire/silent fixtures for the four graftcheck sharding rules.  Every
rule gets its seeded violation AND the paired known-false-positive
shape from the real codebase (runtime axis sizes, dynamic axis names,
lambda donation wrappers, numpy-neutral operands) so FP regressions
break loudly here instead of breaking the --check gate."""

from deepspeed_tpu.analysis import analyze_source
from deepspeed_tpu.analysis.sharding_rules import SHARDING_RULES


def _errors(src, rule, path="<memory>"):
    out = [f for f in analyze_source(src, path, SHARDING_RULES)
           if f.severity == "error" and not f.suppressed]
    return [f for f in out if f.rule == rule]


# ---------------------------------------------------- mesh-axis-unknown
def test_mesh_axis_typo_fires(tmp_path):
    src = (
        "from jax.sharding import PartitionSpec\n"
        "MODEL_AXIS = 'model'\n"
        "DATA_AXIS = 'data'\n"
        "spec = PartitionSpec('data', 'modell')\n")
    p = str(tmp_path / "mod.py")
    (f,) = _errors(src, "mesh-axis-unknown", p)
    assert "modell" in f.message and "model" in f.message


def test_mesh_axis_declared_and_const_ref_silent(tmp_path):
    src = (
        "from jax.sharding import PartitionSpec\n"
        "MODEL_AXIS = 'model'\n"
        "a = PartitionSpec(None, 'model')\n"
        "b = PartitionSpec(MODEL_AXIS)\n"
        "c = PartitionSpec(('model', MODEL_AXIS))\n")
    p = str(tmp_path / "mod.py")
    assert _errors(src, "mesh-axis-unknown", p) == []


def test_mesh_axis_dynamic_name_and_no_universe_silent(tmp_path):
    # known-FP shapes: an axis name held in a runtime variable cannot be
    # validated, and a module with NO statically-declared mesh anywhere
    # must not guess
    src_dyn = (
        "from jax.sharding import PartitionSpec\n"
        "DATA_AXIS = 'data'\n"
        "def make(axis):\n"
        "    return PartitionSpec(axis)\n")
    src_none = (
        "from jax.sharding import PartitionSpec\n"
        "spec = PartitionSpec('anything')\n")
    p = str(tmp_path / "mod.py")
    assert _errors(src_dyn, "mesh-axis-unknown", p) == []
    assert _errors(src_none, "mesh-axis-unknown", p) == []


def test_mesh_axis_project_universe_applies_inside_repo():
    # analyzed at the real repo path, the universe comes from
    # deepspeed_tpu/parallel/mesh.py — no module-local decls needed
    src = (
        "from jax.sharding import PartitionSpec\n"
        "spec = PartitionSpec('modle')\n")
    (f,) = _errors(src, "mesh-axis-unknown",
                   "deepspeed_tpu/parallel/fixture.py")
    assert "modle" in f.message


# ---------------------------------------------------- shard-indivisible
def test_shard_indivisible_fires_on_literal_sizes(tmp_path):
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.sharding import NamedSharding, PartitionSpec\n"
        "MODEL_AXIS = 'model'\n"
        "def setup(mesh_cfg):\n"
        "    mesh = initialize_mesh(model=4)\n"
        "    x = jnp.zeros((8, 130))\n"
        "    return jax.device_put(\n"
        "        x, NamedSharding(mesh, PartitionSpec(None, 'model')))\n")
    p = str(tmp_path / "mod.py")
    (f,) = _errors(src, "shard-indivisible", p)
    assert "130 % 4" in f.message


def test_shard_divisible_and_runtime_sizes_silent(tmp_path):
    ok = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.sharding import NamedSharding, PartitionSpec\n"
        "MODEL_AXIS = 'model'\n"
        "def setup():\n"
        "    mesh = initialize_mesh(model=4)\n"
        "    x = jnp.zeros((8, 128))\n"
        "    return jax.device_put(\n"
        "        x, NamedSharding(mesh, PartitionSpec(None, 'model')))\n")
    # known-FP shape: the axis size is the runtime device count — the
    # rule must stay silent rather than guess a size
    runtime = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.sharding import NamedSharding, PartitionSpec\n"
        "MODEL_AXIS = 'model'\n"
        "def setup(n):\n"
        "    mesh = initialize_mesh(model=n)\n"
        "    x = jnp.zeros((8, 130))\n"
        "    return jax.device_put(\n"
        "        x, NamedSharding(mesh, PartitionSpec(None, 'model')))\n")
    p = str(tmp_path / "mod.py")
    assert _errors(ok, "shard-indivisible", p) == []
    assert _errors(runtime, "shard-indivisible", p) == []


# ----------------------------------------------- donation-alias-mismatch
def test_donation_never_reaches_output_fires(tmp_path):
    src = (
        "import jax\n"
        "def apply(state, grads):\n"
        "    return grads * 2\n"
        "step = jax.jit(apply, donate_argnums=(0,))\n")
    p = str(tmp_path / "mod.py")
    (f,) = _errors(src, "donation-alias-mismatch", p)
    assert "`state`" in f.message


def test_donation_flows_through_assignment_chain_silent(tmp_path):
    src = (
        "import jax\n"
        "def apply(state, grads):\n"
        "    new = state - grads\n"
        "    out = new * 2\n"
        "    return out\n"
        "step = jax.jit(apply, donate_argnums=(0,))\n")
    p = str(tmp_path / "mod.py")
    assert _errors(src, "donation-alias-mismatch", p) == []


def test_donation_lambda_wrapper_silent(tmp_path):
    # known-FP shape: a lambda body is an expression, not a Return
    # statement — the taint must still be seen reaching the result
    src = (
        "import jax\n"
        "step = jax.jit(lambda state, g: update(state, g),\n"
        "               donate_argnums=(0,))\n")
    p = str(tmp_path / "mod.py")
    assert _errors(src, "donation-alias-mismatch", p) == []


# ---------------------------------------------------------- placement-mix
def test_placement_mix_in_traced_fn_fires(tmp_path):
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    a = jax.device_put(x)\n"
        "    b = jnp.zeros((8,))\n"
        "    return a + b\n"
        "g = jax.jit(f)\n")
    p = str(tmp_path / "mod.py")
    (f,) = _errors(src, "placement-mix", p)
    assert "committed" in f.message


def test_placement_mix_numpy_neutral_and_untraced_silent(tmp_path):
    # known-FP shape: numpy operands adopt the committed layout — no mix
    neutral = (
        "import jax\n"
        "import numpy as np\n"
        "def f(x):\n"
        "    a = jax.device_put(x)\n"
        "    c = np.zeros((8,))\n"
        "    return a + c\n"
        "g = jax.jit(f)\n")
    # same mix OUTSIDE traced code: host setup is allowed to stage
    untraced = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def setup(x):\n"
        "    a = jax.device_put(x)\n"
        "    b = jnp.zeros((8,))\n"
        "    return a + b\n")
    p = str(tmp_path / "mod.py")
    assert _errors(neutral, "placement-mix", p) == []
    assert _errors(untraced, "placement-mix", p) == []


# ------------------------------------------------- cross-tier pragmas
def test_check_tier_pragma_not_stale_in_lint_run():
    """A `# graftlint: allow[placement-mix]` pragma must not trip
    unused-pragma when only the lint tier runs (the rule id belongs to
    the --check tier, which did not execute)."""
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    # graftlint: allow[placement-mix] -- staged on purpose\n"
        "    return jax.device_put(x) + jnp.zeros((8,))\n"
        "g = jax.jit(f)\n")
    lint_only = analyze_source(src)  # default: ALL_RULES, no sharding
    assert [f for f in lint_only if f.rule == "unused-pragma"] == []
    # and in a check run the same pragma suppresses the finding
    check = analyze_source(src, "<memory>", SHARDING_RULES)
    mixes = [f for f in check if f.rule == "placement-mix"]
    assert mixes and all(f.suppressed for f in mixes)


# ------------------------------------------ axis-rules table pinning
def test_axis_rules_module_silent_on_both_rules():
    """The REAL rules table (parallel/axis_rules.py) must pass the
    --check sharding tier clean: its mesh-axis names come from the same
    mesh.py constants the analyzer pins against, and its resolution is
    shape-aware (the divisibility guard lives in physical_spec), so a
    finding here is always an analyzer FP regression."""
    import pathlib

    p = (pathlib.Path(__file__).resolve().parents[3]
         / "deepspeed_tpu" / "parallel" / "axis_rules.py")
    src = p.read_text()
    assert _errors(src, "mesh-axis-unknown", str(p)) == []
    assert _errors(src, "shard-indivisible", str(p)) == []


def test_seeded_bad_axis_rule_spec_fires_mesh_axis_unknown():
    """A typo'd mesh axis in a cache-placement spec — the mistake the
    runtime validate_axis_rules guards — fires statically too, at the
    repo path where the project universe (mesh.py) applies."""
    src = (
        "from jax.sharding import PartitionSpec\n"
        "# a hand-rolled cache leaf placement with a typo'd TP axis\n"
        "KV_SPEC = PartitionSpec(None, 'data', 'modle')\n")
    (f,) = _errors(src, "mesh-axis-unknown",
                   "deepspeed_tpu/parallel/fixture.py")
    assert "modle" in f.message and "model" in f.message


def test_seeded_indivisible_cache_placement_fires(tmp_path):
    """A slot-pool cache leaf committed over a data axis that does not
    divide the slot count — the shape physical_spec's divisibility
    fallback exists to prevent — fires shard-indivisible when both
    sizes are static."""
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.sharding import NamedSharding, PartitionSpec\n"
        "DATA_AXIS = 'data'\n"
        "def commit_cache():\n"
        "    mesh = initialize_mesh(data=8)\n"
        "    k = jnp.zeros((2, 6, 4, 8))  # 6 slots on a data=8 mesh\n"
        "    return jax.device_put(\n"
        "        k, NamedSharding(mesh, PartitionSpec(None, 'data')))\n")
    p = str(tmp_path / "mod.py")
    (f,) = _errors(src, "shard-indivisible", p)
    assert "6 % 8" in f.message
