"""Suppression pragmas and the findings baseline: reasoned pragmas
suppress, reasonless ones are themselves errors, stale ones warn, and
baseline fingerprints survive line drift but not source edits."""

import json

from deepspeed_tpu.analysis import (analyze_paths, analyze_source,
                                    load_baseline, write_baseline)

_SCATTER = (
    "def admit(pool, slot, v):\n"
    "    return pool.at[slot].set(v)"
)


def test_pragma_with_reason_suppresses():
    src = _SCATTER + "  # graftlint: allow[unsafe-scatter] -- slot is clamped upstream\n"
    (f,) = [x for x in analyze_source(src) if x.rule == "unsafe-scatter"]
    assert f.suppressed and f.suppress_reason == "slot is clamped upstream"
    assert not f.counts_as_error


def test_pragma_on_comment_line_above_suppresses():
    src = (
        "def admit(pool, slot, v):\n"
        "    # graftlint: allow[unsafe-scatter] -- covers the next line\n"
        "    return pool.at[slot].set(v)\n")
    (f,) = [x for x in analyze_source(src) if x.rule == "unsafe-scatter"]
    assert f.suppressed


def test_pragma_wildcard_and_multi_rule():
    src = _SCATTER + "  # graftlint: allow[*] -- fixture\n"
    (f,) = [x for x in analyze_source(src) if x.rule == "unsafe-scatter"]
    assert f.suppressed
    src2 = _SCATTER + "  # graftlint: allow[unsafe-scatter,recompile-hazard] -- fixture\n"
    findings = analyze_source(src2)
    assert [x for x in findings if x.rule == "unsafe-scatter"][0].suppressed
    # the recompile-hazard half matched nothing, but the pragma as a
    # whole was used — no stale warning
    assert not [x for x in findings if x.rule == "unused-pragma"]


def test_pragma_without_reason_is_an_error_and_does_not_suppress():
    src = _SCATTER + "  # graftlint: allow[unsafe-scatter]\n"
    findings = analyze_source(src)
    scatter = [x for x in findings if x.rule == "unsafe-scatter"][0]
    assert not scatter.suppressed and scatter.counts_as_error
    missing = [x for x in findings if x.rule == "pragma-missing-reason"]
    assert len(missing) == 1 and missing[0].severity == "error"


def test_pragma_wrong_rule_does_not_suppress():
    src = _SCATTER + "  # graftlint: allow[recompile-hazard] -- wrong rule\n"
    findings = analyze_source(src)
    assert [x for x in findings
            if x.rule == "unsafe-scatter"][0].counts_as_error
    assert [x for x in findings if x.rule == "unused-pragma"]


def test_unused_pragma_warns():
    src = "x = 1  # graftlint: allow[unsafe-scatter] -- nothing here\n"
    (f,) = analyze_source(src)
    assert f.rule == "unused-pragma" and f.severity == "warning"


# ------------------------------------------------------------- baseline
def _write_module(tmp_path, body):
    p = tmp_path / "mod.py"
    p.write_text(body)
    return str(p)


def test_baseline_round_trip(tmp_path):
    mod = _write_module(tmp_path, _SCATTER + "\n")
    bl = str(tmp_path / "baseline.json")

    rep = analyze_paths([mod])
    assert rep.errors == 1
    n = write_baseline(bl, rep.findings)
    assert n == 1
    assert len(load_baseline(bl)) == 1

    rep2 = analyze_paths([mod], baseline=bl)
    assert rep2.errors == 0 and rep2.baselined == 1
    doc = rep2.to_dict()
    assert doc["summary"]["baselined"] == 1
    assert doc["summary"]["errors"] == 0


def test_baseline_survives_line_drift(tmp_path):
    mod = _write_module(tmp_path, _SCATTER + "\n")
    bl = str(tmp_path / "baseline.json")
    write_baseline(bl, analyze_paths([mod]).findings)

    # prepend unrelated code: the finding moves down two lines but its
    # fingerprint (rule + file + function + normalised text) holds
    _write_module(tmp_path, "import math\nK = 3\n" + _SCATTER + "\n")
    rep = analyze_paths([mod], baseline=bl)
    assert rep.errors == 0 and rep.baselined == 1


def test_baseline_invalidated_by_source_edit(tmp_path):
    mod = _write_module(tmp_path, _SCATTER + "\n")
    bl = str(tmp_path / "baseline.json")
    write_baseline(bl, analyze_paths([mod]).findings)

    # the flagged line itself changes -> the grandfathered entry no
    # longer matches and the finding comes back as a live error
    _write_module(
        tmp_path,
        "def admit(pool, slot, v):\n"
        "    return pool.at[slot].add(v)\n")
    rep = analyze_paths([mod], baseline=bl)
    assert rep.errors == 1 and rep.baselined == 0


def test_baseline_rejects_foreign_json(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"not": "a baseline"}))
    try:
        load_baseline(str(bad))
    except ValueError:
        pass
    else:
        raise AssertionError("expected ValueError on foreign JSON")
