"""Per-op fixtures for graftcheck's abstract shape/dtype transfer
functions: each rule must produce the exact abstract result shape for
its op (gather/take, scatter/dynamic-update-slice, concatenate,
reshape, broadcast), and symbolic dims must flow through arithmetic
without collapsing to Unbounded.  Pure absdomain values in, no jax."""

from deepspeed_tpu.analysis.absdomain import (HOST, UNCOMMITTED, Arr,
                                              FiniteSet, IntRange, Known,
                                              Scalar, Tup, Unbounded,
                                              Unknown, pow2_buckets)
from deepspeed_tpu.analysis.shape_rules import (RULES, binop,
                                                broadcast_shapes,
                                                method_call)


def _dims(shape):
    return tuple(d.values() for d in shape)


# ------------------------------------------------------------- gather
def test_take_along_axis_adopts_index_shape():
    x = Arr((Known(8), Known(256)), "float32", HOST)
    idx = Arr((Known(8), Known(1)), "int32", HOST)
    out = RULES["jnp.take_along_axis"]([x, idx], {})
    assert isinstance(out, Arr)
    assert _dims(out.shape) == ((8,), (1,)) and out.dtype == "float32"


def test_take_with_axis_splices_index_shape():
    x = Arr((Known(4), Known(32), Known(64)), "float32", HOST)
    idx = Arr((Known(5),), "int32", HOST)
    out = RULES["jnp.take"]([x, idx], {"axis": Scalar(1)})
    assert isinstance(out, Arr)
    assert _dims(out.shape) == ((4,), (5,), (64,))


def test_take_symbolic_axis_escapes_to_unknown():
    x = Arr((Known(4), Known(32)), "float32", HOST)
    idx = Arr((Known(5),), "int32", HOST)
    out = RULES["jnp.take"]([x, idx], {"axis": Scalar(Unbounded("n"))})
    assert isinstance(out, Unknown)


# ------------------------------------- scatter / dynamic update slice
def test_dynamic_update_slice_keeps_destination_shape():
    dst = Arr((Known(8), Known(1024)), "int32", HOST)
    upd = Arr((Known(1), IntRange(16, 256)), "int32", HOST)
    out = RULES["jax.lax.dynamic_update_slice"](
        [dst, upd, Scalar(0), Scalar(Unbounded("pos"))], {})
    assert out is dst  # scatter result == destination, symbolic or not


def test_dynamic_slice_in_dim_replaces_one_axis():
    x = Arr((Known(8), Known(1024)), "float32", HOST)
    out = RULES["jax.lax.dynamic_slice_in_dim"](
        [x, Scalar(Unbounded("start")), Scalar(Known(256)), Scalar(1)], {})
    assert isinstance(out, Arr)
    assert _dims(out.shape) == ((8,), (256,))
    # an unbounded SIZE flows through as an Unbounded dim — it only
    # becomes a finding if the value reaches a watched jit operand
    out2 = RULES["jax.lax.dynamic_slice_in_dim"](
        [x, Scalar(0), Scalar(Unbounded("n")), Scalar(1)], {})
    assert isinstance(out2, Arr)
    assert isinstance(out2.shape[1], Unbounded)


# -------------------------------------------------------- concatenate
def test_concatenate_sums_known_axis():
    a = Arr((Known(96),), "int32", HOST)
    b = Arr((Known(32),), "int32", HOST)
    out = RULES["np.concatenate"]([Tup([a, b])], {})
    assert isinstance(out, Arr) and _dims(out.shape) == ((128,),)


def test_concatenate_symbolic_part_goes_unbounded_not_wrong():
    a = Arr((Known(96),), "int32", HOST)
    b = Arr((IntRange(8, 32),), "int32", HOST)
    out = RULES["np.concatenate"]([Tup([a, b])], {})
    assert isinstance(out, Arr)
    assert isinstance(out.shape[0], Unbounded)  # honest imprecision


# ------------------------------------------------- reshape/broadcast
def test_reshape_with_literal_shape_and_wildcard():
    x = Arr((Known(4), Known(8)), "float32", HOST)
    out = RULES["jnp.reshape"]([x, Tup([Scalar(2), Scalar(16)])], {})
    assert isinstance(out, Arr) and _dims(out.shape) == ((2,), (16,))
    out2 = RULES["jnp.reshape"]([x, Tup([Scalar(-1), Scalar(8)])], {})
    assert isinstance(out2, Arr) and _dims(out2.shape) == ((4,), (8,))


def test_reshape_wildcard_over_symbolic_operand_is_unknown():
    x = Arr((IntRange(16, 256),), "float32", HOST)
    out = RULES["jnp.reshape"]([x, Tup([Scalar(-1), Scalar(8)])], {})
    assert isinstance(out, Unknown)


def test_broadcast_to_adopts_target_shape():
    x = Arr((Known(1),), "float32", UNCOMMITTED)
    out = RULES["jnp.broadcast_to"]([x, Tup([Scalar(8), Scalar(4)])], {})
    assert isinstance(out, Arr) and _dims(out.shape) == ((8,), (4,))
    assert out.placement == UNCOMMITTED


def test_broadcast_shapes_symbolic_dim_survives():
    w = pow2_buckets(16, 256)
    out = broadcast_shapes((Known(1), w), (Known(8), Known(1)))
    assert out[0].values() == (8,)
    assert out[1] is w  # the SAME Dim object: joint expansion preserved


def test_binop_correlates_via_shared_dim_object():
    b = FiniteSet([1, 2, 4], "B")
    x = Arr((b, Known(1)), "float32", HOST)
    y = Arr((b, Known(1)), "float32", HOST)
    out = binop(x, y)
    assert isinstance(out, Arr) and out.shape[0] is b


# --------------------------------------------------- constructors etc.
def test_constructors_pin_placement_and_dtype():
    z = RULES["np.zeros"]([Tup([Scalar(8)])], {})
    assert z.placement == HOST and z.dtype == "float64"
    j = RULES["jnp.zeros"]([Tup([Scalar(8)])],
                           {"dtype": Scalar("int32")})
    assert j.placement == UNCOMMITTED and j.dtype == "int32"
    f = RULES["np.full"]([Tup([Scalar(IntRange(16, 32))]), Scalar(7)], {})
    assert isinstance(f, Arr) and f.dtype == "int64"
    assert f.shape[0].values() == tuple(range(16, 33))


def test_asarray_preserves_placement_astype_preserves_shape():
    host = Arr((Known(8),), "float64", HOST)
    out = RULES["jnp.asarray"]([host], {"dtype": Scalar("int32")})
    assert out.placement == HOST and out.dtype == "int32"  # no commit
    m = method_call(host, "astype", [Scalar("int32")], {})
    assert isinstance(m, Arr) and m.dtype == "int32"
    assert _dims(m.shape) == ((8,),)


def test_item_and_tolist_are_host_escapes():
    x = Arr((), "int32", HOST)
    assert isinstance(method_call(x, "item", [], {}), Unknown)
    assert isinstance(method_call(x, "tolist", [], {}), Unknown)


# ------------------------------------------------ symbolic arithmetic
def test_symbolic_dim_value_sets():
    assert pow2_buckets(16, 256).values() == (16, 32, 64, 128, 256)
    assert IntRange(2, 5).values() == (2, 3, 4, 5)
    assert IntRange(1, 10_000).values() is None  # over the 512 cap
    assert Unbounded("n").values() is None
    assert FiniteSet([4, 2, 2]).values() == (2, 4)
