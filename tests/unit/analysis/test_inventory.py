"""Jit-inventory drift test: graftlint statically enumerates every
jit-wrapper binding under serving/, and this test cross-checks that set
against the recompile watchdog's watch lists — a new ``self._foo =
jax.jit(...)`` in serving code fails here until it is either added to a
watch list (so post-warmup recompiles are attributed) or explicitly
justified below."""

import os

import deepspeed_tpu
from deepspeed_tpu.analysis import jit_inventory
from deepspeed_tpu.serving import engine as engine_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    deepspeed_tpu.__file__)))
SERVING = os.path.join(REPO, "deepspeed_tpu", "serving")
FRONTEND = os.path.join(REPO, "deepspeed_tpu", "serving", "frontend")
INFERENCE = os.path.join(REPO, "deepspeed_tpu", "inference")


def _watched():
    return (set(engine_mod._WATCHED_ENGINE_JITS)
            | set(engine_mod._WATCHED_POOL_JITS)
            | set(engine_mod._WATCHED_SERVING_JITS)
            | set(engine_mod._WATCHED_DRAFTER_JITS))


def test_every_serving_jit_is_watchdog_covered():
    inv = jit_inventory([SERVING])
    assert inv, "static jit inventory came back empty — analyzer broken?"
    unwatched = sorted({e["attr"] for e in inv} - _watched())
    assert not unwatched, (
        f"jitted entry points in serving/ not covered by any watchdog "
        f"watch list: {unwatched} — attach them in "
        "ServingEngine._ensure_watch or justify an allowlist here")


def test_inventory_finds_the_known_entry_points():
    """Pin the inventory itself: the analyzer must keep seeing the jits
    we know exist (an empty/blind inventory would make the coverage
    assertion above pass vacuously)."""
    inv = jit_inventory([SERVING])
    by_attr = {e["attr"]: e for e in inv}
    # contiguous pool: donated admit paths
    assert by_attr["_admit_jit"]["donate_argnums"] == [0]
    assert by_attr["_admit_rows_jit"]["donate_argnums"] == [0]
    # paged pool: donated cache arg sits at position 1 (after params),
    # verify carries static draft-shape argnums
    assert by_attr["_paged_decode_jit"]["donate_argnums"] == [1]
    assert by_attr["_paged_verify_jit"]["static_argnums"] == [9, 10]
    assert by_attr["_paged_chunk_jit"]["donate_argnums"] == [1]
    assert by_attr["_jit_copy_page"]["donate_argnums"] == [0]
    # engine-local guard jit + the drafter's lazily-built argmax (the
    # escape the inventory originally caught)
    assert by_attr["_jit_finite"]["class"] == "ServingEngine"
    assert by_attr["_argmax"]["class"] == "SmallModelDrafter"


def test_frontend_has_zero_jits():
    """The async front end is pure host code by design — the engine's
    compiled surface must not grow when the HTTP/bridge/priority layer
    lands.  Any jit binding appearing under serving/frontend/ is
    inventory drift and fails here until it is watch-listed (and the
    design doc explaining why the front end compiles nothing is
    updated)."""
    inv = jit_inventory([FRONTEND])
    assert inv == [], (
        f"serving/frontend/ grew jitted entry points: "
        f"{sorted(e['attr'] for e in inv)}")


def test_watched_engine_jits_exist_in_inference_inventory():
    """The engine watch list names attributes of InferenceEngine; each
    must correspond to a real jit binding in inference/ (typo'd watch
    entries silently no-op at attach time — attach skips absentees)."""
    inv_attrs = {e["attr"] for e in jit_inventory([INFERENCE])}
    missing = sorted(set(engine_mod._WATCHED_ENGINE_JITS) - inv_attrs)
    assert not missing, (
        f"watch-listed engine jits with no jax.jit binding under "
        f"inference/: {missing}")
