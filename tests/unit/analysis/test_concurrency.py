"""graftsync fixtures and drift tests.

Every sync rule must FIRE on its seeded violation and stay SILENT on
the paired known-false-positive shape (executor-wrapped blocking call,
``call_soon_threadsafe``-wrapped resolution, lock released before the
``await``, both-sides-locked shared write).  The thread-context map is
then pinned against the real front end in both directions, like
``test_inventory.py`` pins the jit inventory: every coroutine must
infer LOOP, every ``step()`` caller must infer ENGINE, and the named
bridge crossings must keep their exact labels.
"""

import ast
import json
import os
import subprocess
import sys
import time

import deepspeed_tpu
from deepspeed_tpu.analysis import (SYNC_RULE_IDS, SYNC_RULES,
                                    ThreadContextMap, analyze_source,
                                    iter_python_files, thread_inventory)
from deepspeed_tpu.analysis.dataflow import ModuleIndex, node_path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    deepspeed_tpu.__file__)))
FRONTEND = os.path.join(REPO, "deepspeed_tpu", "serving", "frontend")
GRAFTLINT = os.path.join(REPO, "bin", "graftlint")


def _errors(src, rule=None):
    out = [f for f in analyze_source(src, rules=SYNC_RULES)
           if f.severity == "error" and not f.suppressed]
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


# ------------------------------------- blocking-call-in-coroutine
def test_blocking_sleep_in_coroutine_fires():
    src = (
        "import time\n"
        "async def handler():\n"
        "    time.sleep(0.1)\n")
    (f,) = _errors(src, "blocking-call-in-coroutine")
    assert f.line == 3 and "time.sleep" in f.message


def test_blocking_variants_fire():
    src = (
        "import queue\n"
        "import threading\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        self._ops = queue.Queue()\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "    def _run(self):\n"
        "        pass\n"
        "    async def h(self, srv, sock, x):\n"
        "        fh = open('/tmp/x')\n"
        "        sock.recv(4096)\n"
        "        srv.step()\n"
        "        x.block_until_ready()\n"
        "        self._t.join()\n"
        "        self._ops.get()\n")
    found = _errors(src, "blocking-call-in-coroutine")
    assert len(found) == 6, [f.message for f in found]
    blob = " ".join(f.message for f in found)
    for needle in ("file I/O", ".recv", "step()", "block_until_ready",
                   ".join()", ".get()"):
        assert needle in blob, (needle, blob)


def test_blocking_known_fp_shapes_stay_silent():
    # executor handoff, awaited async equivalents, and non-blocking
    # queue access are the sanctioned idioms — none may fire
    src = (
        "import asyncio\n"
        "import queue\n"
        "import time\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        self._ops = queue.Queue()\n"
        "    async def h(self, loop, t):\n"
        "        await asyncio.sleep(0.1)\n"
        "        def work():\n"
        "            time.sleep(1.0)\n"
        "        await loop.run_in_executor(None, work)\n"
        "        await loop.run_in_executor(None, t.join)\n"
        "        self._ops.get_nowait()\n"
        "        self._ops.get(block=False)\n")
    assert _errors(src, "blocking-call-in-coroutine") == []


# ------------------------------------- cross-thread-engine-access
def test_cross_thread_engine_read_fires():
    src = (
        "class Frontend:\n"
        "    async def stats(self):\n"
        "        return self.srv.scheduler.pending\n")
    (f,) = _errors(src, "cross-thread-engine-access")
    assert "self.srv.scheduler" in f.message and "bridge.call" in f.message


def test_cross_thread_engine_write_fires():
    src = (
        "class Frontend:\n"
        "    async def pause(self, srv):\n"
        "        srv.paused = True\n")
    (f,) = _errors(src, "cross-thread-engine-access")
    assert "srv.paused" in f.message


def test_bridge_call_handoff_stays_silent():
    # the sanctioned read path: the lambda/function handed to
    # bridge.call runs on the step thread, so its engine access is legal
    src = (
        "class Frontend:\n"
        "    async def stats(self):\n"
        "        n = await self.bridge.call(\n"
        "            lambda srv: srv.scheduler.pending)\n"
        "        def probe(srv):\n"
        "            return srv.live_count\n"
        "        m = await self.bridge.call(probe)\n"
        "        return n + m\n")
    assert _errors(src, "cross-thread-engine-access") == []


# --------------------------------------- unsafe-future-resolution
def test_off_loop_set_result_fires():
    src = (
        "import threading\n"
        "def worker(fut):\n"
        "    fut.set_result(1)\n"
        "t = threading.Thread(target=worker)\n")
    (f,) = _errors(src, "unsafe-future-resolution")
    assert "call_soon_threadsafe" in f.message


def test_call_soon_threadsafe_wrapped_resolution_stays_silent():
    # the bridge's _resolve shape: the setter runs as a loop callback,
    # so its set_result is on-loop even though the scheduler is not
    src = (
        "import threading\n"
        "class B:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self.worker)\n"
        "    def worker(self):\n"
        "        self.loop.call_soon_threadsafe(self._set, self.fut, 1)\n"
        "    def _set(self, fut, v):\n"
        "        if not fut.done():\n"
        "            fut.set_result(v)\n")
    assert _errors(src, "unsafe-future-resolution") == []


def test_concurrent_futures_receiver_stays_silent():
    src = (
        "import threading\n"
        "def worker(fut: 'concurrent.futures.Future'):\n"
        "    fut.set_result(1)\n"
        "t = threading.Thread(target=worker)\n")
    assert _errors(src, "unsafe-future-resolution") == []


# --------------------------------------- await-while-holding-lock
def test_await_inside_lock_fires():
    src = (
        "import threading\n"
        "_lock = threading.Lock()\n"
        "async def h(q):\n"
        "    with _lock:\n"
        "        item = await q.get()\n"
        "    return item\n")
    (f,) = _errors(src, "await-while-holding-lock")
    assert f.line == 5 and "_lock" in f.message


def test_lock_released_before_await_stays_silent():
    src = (
        "import threading\n"
        "_lock = threading.Lock()\n"
        "async def h(q):\n"
        "    with _lock:\n"
        "        item = prepare()\n"
        "    return await q.put(item)\n")
    assert _errors(src, "await-while-holding-lock") == []


def test_inconsistent_lock_order_fires_once():
    src = (
        "import threading\n"
        "a = threading.Lock()\n"
        "b = threading.Lock()\n"
        "def f():\n"
        "    with a:\n"
        "        with b:\n"
        "            pass\n"
        "def g():\n"
        "    with b:\n"
        "        with a:\n"
        "            pass\n")
    (f,) = _errors(src, "await-while-holding-lock")
    assert "AB/BA" in f.message or "opposite order" in f.message


def test_consistent_lock_order_stays_silent():
    src = (
        "import threading\n"
        "a = threading.Lock()\n"
        "b = threading.Lock()\n"
        "def f():\n"
        "    with a:\n"
        "        with b:\n"
        "            pass\n"
        "def g():\n"
        "    with a:\n"
        "        with b:\n"
        "            pass\n")
    assert _errors(src, "await-while-holding-lock") == []


# ----------------------------------------- unguarded-shared-write
_SHARED_WRITE_SRC = (
    "import threading\n"
    "class B:\n"
    "    def __init__(self):\n"
    "        self._lk = threading.Lock()\n"
    "    def start(self):\n"
    "        self._t = threading.Thread(target=self._run)\n"
    "    async def stop(self):\n"
    "        {loop_write}\n"
    "    def _run(self):\n"
    "        {engine_write}\n")


def test_unguarded_shared_write_fires():
    src = _SHARED_WRITE_SRC.format(loop_write="self.items.clear()",
                                   engine_write="self.items[1] = 2")
    (f,) = _errors(src, "unguarded-shared-write")
    assert "self.items" in f.message and "LOOP" in f.message \
        and "ENGINE" in f.message


def test_both_sides_locked_stays_silent():
    src = _SHARED_WRITE_SRC.format(
        loop_write="\n        ".join(
            ["with self._lk:", "    self.items.clear()"]),
        engine_write="\n        ".join(
            ["with self._lk:", "    self.items[1] = 2"]))
    assert _errors(src, "unguarded-shared-write") == []


def test_single_sided_write_stays_silent():
    src = _SHARED_WRITE_SRC.format(loop_write="pass",
                                   engine_write="self.items[1] = 2")
    assert _errors(src, "unguarded-shared-write") == []


# ---------------------------------------- thread-context map drift
def _frontend_maps():
    out = {}
    for fp in iter_python_files([FRONTEND]):
        with open(fp, encoding="utf-8") as fh:
            source = fh.read()
        tree = ast.parse(source, filename=fp)
        index = ModuleIndex(tree)
        out[os.path.basename(fp)] = (index,
                                     ThreadContextMap(index).labels())
    return out


def test_every_frontend_coroutine_is_loop():
    """Direction 1: each `async def` in serving/frontend infers exactly
    LOOP — a coroutine drifting to ENGINE/BOTH means the inference (or
    the front end's threading discipline) broke."""
    checked = 0
    for fname, (index, labels) in _frontend_maps().items():
        for fi in index.functions.values():
            if not isinstance(fi.node, ast.AsyncFunctionDef):
                continue
            checked += 1
            assert labels.get(fi.qualname) == "LOOP", (
                f"{fname}:{fi.qualname} inferred "
                f"{labels.get(fi.qualname)}, expected LOOP")
    assert checked >= 10, f"only {checked} coroutines found — drift?"


def test_every_step_caller_is_engine_only():
    """Direction 2: any frontend function that calls `.step()` on an
    engine root must infer exactly ENGINE — step() leaking into LOOP
    or BOTH context is the incident this tier exists to prevent."""
    checked = 0
    for fname, (index, labels) in _frontend_maps().items():
        for fi in index.functions.values():
            calls_step = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "step"
                and (node_path(n.func.value) or "").split(".")[-1]
                    .lstrip("_") in ("srv", "engine")
                for n in ast.walk(fi.node))
            if not calls_step:
                continue
            checked += 1
            assert labels.get(fi.qualname) == "ENGINE", (
                f"{fname}:{fi.qualname} calls step() but inferred "
                f"{labels.get(fi.qualname)}")
    assert checked >= 1, "no step() caller found in frontend — drift?"


def test_bridge_crossing_labels_pinned():
    """The named crossings keep their exact labels: the
    call_soon_threadsafe callbacks are LOOP, the op-queue consumers are
    ENGINE, and _emit (called from stop() and the step thread) is the
    one BOTH function."""
    _, labels = _frontend_maps()["bridge.py"]
    expected = {
        "AsyncEngineBridge.start": "LOOP",
        "AsyncEngineBridge.stop": "LOOP",
        "AsyncEngineBridge.submit": "LOOP",
        "AsyncEngineBridge.call": "LOOP",
        "AsyncEngineBridge._set_result": "LOOP",
        "AsyncEngineBridge._set_exception": "LOOP",
        "AsyncEngineBridge._deliver": "LOOP",
        "AsyncEngineBridge._run": "ENGINE",
        "AsyncEngineBridge._loop_body": "ENGINE",
        "AsyncEngineBridge._apply_op": "ENGINE",
        "AsyncEngineBridge._fan_out": "ENGINE",
        "AsyncEngineBridge._emit": "BOTH",
        "AsyncEngineBridge._reject_pending_ops": "BOTH",
        # _reject is reachable from _apply_op (ENGINE) and from stop()'s
        # leftover-op rejection (LOOP) — safe on both sides because it
        # marshals through call_soon_threadsafe
        "AsyncEngineBridge._reject": "BOTH",
        "AsyncEngineBridge._resolve": "ENGINE",
    }
    for qual, want in expected.items():
        assert labels.get(qual) == want, (qual, labels.get(qual), want)
    # and BOTH stays the exception, not the rule: only the documented
    # crossing helpers may run on either side
    both = sorted(q for q, v in labels.items() if v == "BOTH")
    assert both == ["AsyncEngineBridge._emit",
                    "AsyncEngineBridge._reject",
                    "AsyncEngineBridge._reject_pending_ops"], both


def test_thread_inventory_matches_cli_dump():
    inv = thread_inventory([FRONTEND])
    by_base = {os.path.basename(k): v for k, v in inv.items()}
    assert by_base["bridge.py"]["AsyncEngineBridge._apply_op"] == "ENGINE"
    proc1 = subprocess.run(
        [sys.executable, GRAFTLINT, "--threads",
         os.path.join("deepspeed_tpu", "serving", "frontend")],
        capture_output=True, text=True, timeout=120, cwd=str(REPO))
    assert proc1.returncode == 0, proc1.stdout + proc1.stderr
    doc = json.loads(proc1.stdout)
    assert doc["version"] == 1
    cli_by_base = {os.path.basename(k): v
                   for k, v in doc["files"].items()}
    assert cli_by_base == by_base
    # reproducible: a second run emits byte-identical JSON
    proc2 = subprocess.run(
        [sys.executable, GRAFTLINT, "--threads",
         os.path.join("deepspeed_tpu", "serving", "frontend")],
        capture_output=True, text=True, timeout=120, cwd=str(REPO))
    assert proc2.stdout == proc1.stdout


# ------------------------------------------------ CLI tier budget
def test_sync_cli_under_two_seconds_without_jax():
    """`bin/graftlint --tier sync` over the gated surface: exit 0,
    < 2 s, and the standalone loader must never pull in jax."""
    surface = [os.path.join("deepspeed_tpu", "serving", "frontend"),
               os.path.join("deepspeed_tpu", "serving", "engine.py"),
               os.path.join("deepspeed_tpu", "telemetry")]
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, GRAFTLINT, "--tier", "sync"] + surface,
        capture_output=True, text=True, timeout=60, cwd=str(REPO))
    wall = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert wall < 2.0, f"--tier sync took {wall:.2f}s (budget 2s)"
    probe = subprocess.run(
        [sys.executable, "-c",
         "import runpy, sys\n"
         "sys.argv = ['graftlint', '--tier', 'sync'] + %r\n"
         "try:\n"
         "    runpy.run_path(%r, run_name='__main__')\n"
         "except SystemExit as e:\n"
         "    assert e.code == 0, e.code\n"
         "assert 'jax' not in sys.modules, 'graftlint imported jax'\n"
         % (surface, GRAFTLINT)],
        capture_output=True, text=True, timeout=60, cwd=str(REPO))
    assert probe.returncode == 0, probe.stdout + probe.stderr


def test_sync_cli_fails_on_seeded_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import time\n"
                   "async def handler():\n"
                   "    time.sleep(1)\n")
    proc = subprocess.run(
        [sys.executable, GRAFTLINT, "--tier", "sync", str(bad)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "blocking-call-in-coroutine" in proc.stdout
    # the default all-tiers run catches it too
    proc2 = subprocess.run(
        [sys.executable, GRAFTLINT, str(bad)],
        capture_output=True, text=True, timeout=60)
    assert proc2.returncode == 1
    assert "blocking-call-in-coroutine" in proc2.stdout


def test_sync_rule_ids_are_pragma_addressable():
    # a reasoned pragma must suppress each sync rule (the triage
    # workflow depends on it)
    src = (
        "import time\n"
        "async def handler():\n"
        "    time.sleep(1)  # graftlint: allow[blocking-call-in-coroutine]"
        " -- fixture: deliberate\n")
    out = analyze_source(src, rules=SYNC_RULES)
    assert [f.rule for f in out if f.suppressed] == \
        ["blocking-call-in-coroutine"]
    assert not [f for f in out if f.counts_as_error]
    assert SYNC_RULE_IDS == {r.id for r in SYNC_RULES}
