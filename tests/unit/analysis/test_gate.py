"""The CI gate: graftlint over serving/ + telemetry/ must report zero
unsuppressed errors, and every suppression must carry a reason.  Pure
AST analysis — no tracing, runs in well under a second — so this sits
in tier-1 and fails the suite the moment a trace-safety invariant is
broken on paper, before any jit runs."""

import json
import os
import subprocess
import sys

import deepspeed_tpu
from deepspeed_tpu.analysis import (ALL_RULES, CHECK_RULE_IDS, OWN_RULES,
                                    SHARDING_RULES, SYNC_RULE_IDS,
                                    SYNC_RULES, analyze_paths,
                                    check_paths, iter_python_files)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    deepspeed_tpu.__file__)))
GATE_PATHS = [os.path.join(REPO, "deepspeed_tpu", "serving"),
              os.path.join(REPO, "deepspeed_tpu", "telemetry"),
              os.path.join(REPO, "deepspeed_tpu", "parallel"),
              os.path.join(REPO, "deepspeed_tpu", "runtime", "engine.py")]
FRONTEND = os.path.join(REPO, "deepspeed_tpu", "serving", "frontend")


def test_gate_zero_unsuppressed_errors():
    rep = analyze_paths(GATE_PATHS)
    offenders = [f.format_human() for f in rep.findings
                 if f.counts_as_error]
    assert rep.errors == 0, (
        "graftlint gate broken — fix the finding or add a reasoned "
        "pragma:\n" + "\n".join(offenders))
    assert rep.warnings == 0, [f.format_human() for f in rep.findings
                               if f.severity == "warning"]


def test_gate_covers_serving_frontend():
    """The async front end (bridge/server/priority) is inside the
    serving/ gate path by recursion, but pin it explicitly: the step
    thread is the one seam where host code touches the engine every
    step, so hot-loop-host-sync must keep seeing these files — and
    they must hold at zero findings, with pragmas allowed ONLY for the
    graftsync tier (the bridge's documented deliberate crossings; the
    lint tier still has nothing to suppress in pure host code)."""
    rep = analyze_paths([FRONTEND])
    assert rep.files >= 4, (
        f"frontend scan saw only {rep.files} files — gate lost "
        "serving/frontend/")
    assert rep.errors == 0 and rep.warnings == 0, [
        f.format_human() for f in rep.findings]
    non_sync = [f.format_human() for f in rep.findings
                if f.suppressed and f.rule not in SYNC_RULE_IDS]
    assert not non_sync, (
        "frontend should need no lint-tier pragmas — it must stay pure "
        "host code:\n" + "\n".join(non_sync))
    # and the recursive serving/ gate really does include these files
    gate_files = {f for f in iter_python_files(GATE_PATHS)}
    frontend_files = set(iter_python_files([FRONTEND]))
    assert frontend_files <= gate_files, (
        sorted(frontend_files - gate_files))


def test_gate_every_suppression_carries_a_reason():
    rep = analyze_paths(GATE_PATHS)
    assert rep.suppressed > 0, (
        "expected the documented deliberate host syncs to be pragma'd")
    for f in rep.findings:
        if f.suppressed:
            assert f.suppress_reason, f.format_human()


def test_gate_runs_every_rule():
    # the gate must not silently run with a subset of the catalog
    assert {r.id for r in ALL_RULES} == {
        "recompile-hazard", "uncommitted-buffer", "donation-after-use",
        "unsafe-scatter", "hot-loop-host-sync"}
    assert {r.id for r in SYNC_RULES} == {
        "blocking-call-in-coroutine", "cross-thread-engine-access",
        "unsafe-future-resolution", "await-while-holding-lock",
        "unguarded-shared-write"}
    assert {r.id for r in OWN_RULES} == {
        "leak-on-exception-path", "double-release", "use-after-release",
        "unbalanced-refcount", "missing-rollback"}
    assert {r.id for r in SHARDING_RULES} == {
        "mesh-axis-unknown", "shard-indivisible",
        "donation-alias-mismatch", "placement-mix"}
    assert CHECK_RULE_IDS == {r.id for r in SHARDING_RULES} | {
        "signature-escape", "unbounded-signature"}


def test_sync_gate_zero_unsuppressed_errors():
    """The graftsync tier alone over its gated surface (the concurrent
    seam: frontend + engine + telemetry) holds at zero unsuppressed
    errors, with every deliberate crossing pragma'd with a reason."""
    surface = [os.path.join(REPO, "deepspeed_tpu", "serving", "frontend"),
               os.path.join(REPO, "deepspeed_tpu", "serving", "engine.py"),
               os.path.join(REPO, "deepspeed_tpu", "telemetry")]
    rep = analyze_paths(surface, rules=SYNC_RULES)
    offenders = [f.format_human() for f in rep.findings
                 if f.counts_as_error]
    assert rep.errors == 0, (
        "graftsync gate broken — fix the finding or add a reasoned "
        "pragma:\n" + "\n".join(offenders))
    assert rep.warnings == 0, [f.format_human() for f in rep.findings
                               if f.severity == "warning"]
    assert rep.suppressed > 0, (
        "expected the bridge's documented crossings to be pragma'd")
    for f in rep.findings:
        if f.suppressed:
            assert f.rule in SYNC_RULE_IDS, f.format_human()
            assert f.suppress_reason, f.format_human()


def test_own_gate_zero_unsuppressed_errors():
    """The graftown tier alone over its gated surface (all of serving/,
    where every slot/page/future lifecycle lives) holds at zero
    unsuppressed errors with NO baseline and NO pragmas — the tier was
    triaged by fixing code, not by grandfathering findings."""
    surface = [os.path.join(REPO, "deepspeed_tpu", "serving")]
    rep = analyze_paths(surface, rules=OWN_RULES)
    offenders = [f.format_human() for f in rep.findings
                 if f.counts_as_error]
    assert rep.errors == 0, (
        "graftown gate broken — fix the finding or add a reasoned "
        "pragma:\n" + "\n".join(offenders))
    assert rep.warnings == 0, [f.format_human() for f in rep.findings
                               if f.severity == "warning"]
    assert rep.suppressed == 0 and rep.baselined == 0, (
        "the own tier holds with no suppressions at all: "
        + "\n".join(f.format_human() for f in rep.findings))


def test_check_tier_gate_zero_unsuppressed_errors():
    """The --check tier (lint + sharding + signature enumeration) over
    the full gate holds at zero unsuppressed errors too."""
    rep = check_paths(GATE_PATHS, root=REPO)
    offenders = [f.format_human() for f in rep.findings
                 if f.counts_as_error]
    assert rep.errors == 0, (
        "graftcheck gate broken — fix the finding or add a reasoned "
        "pragma:\n" + "\n".join(offenders))
    assert rep.warnings == 0, [f.format_human() for f in rep.findings
                               if f.severity == "warning"]


def test_check_cli_under_two_seconds_without_jax():
    """`bin/graftlint --check` is the CI entry point: exit 0, < 2 s,
    and the standalone loader must never pull in jax."""
    import time

    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "graftlint"),
         "--check"],
        capture_output=True, text=True, timeout=60,
        cwd=str(REPO))
    wall = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert wall < 2.0, f"--check took {wall:.2f}s (budget 2s)"
    probe = subprocess.run(
        [sys.executable, "-c",
         "import runpy, sys\n"
         "sys.argv = ['graftlint', '--check']\n"
         "try:\n"
         "    runpy.run_path(%r, run_name='__main__')\n"
         "except SystemExit as e:\n"
         "    assert e.code == 0, e.code\n"
         "assert 'jax' not in sys.modules, 'graftlint imported jax'\n"
         % os.path.join(REPO, "bin", "graftlint")],
        capture_output=True, text=True, timeout=60, cwd=str(REPO))
    assert probe.returncode == 0, probe.stdout + probe.stderr


def test_cli_json_schema_and_exit_code():
    """`bin/graftlint --json` is the standalone gate: exit 0 and a
    stable {version, summary, findings} document."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "graftlint"),
         "--json"] + GATE_PATHS,
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == 1
    summary = doc["summary"]
    assert summary["errors"] == 0
    assert {"files", "total", "errors", "warnings", "suppressed",
            "baselined"} <= set(summary)
    for f in doc["findings"]:
        assert {"rule", "severity", "path", "line", "message",
                "fingerprint"} <= set(f)


def test_cli_fails_on_seeded_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(pool, slot, v):\n"
                   "    return pool.at[slot].set(v)\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "graftlint"), str(bad)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "unsafe-scatter" in proc.stdout
    # bad path -> usage error, distinct from gate failure
    proc2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "graftlint"),
         str(tmp_path / "missing.py")],
        capture_output=True, text=True, timeout=60)
    assert proc2.returncode == 2
