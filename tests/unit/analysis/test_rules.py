"""Per-rule fixtures: each graftlint rule must FIRE on its seeded
violation and stay SILENT on the paired safe idiom.  The negative
fixtures pin the known false-positive shapes from the real codebase
(``jnp.zeros`` handed straight to ``device_put``, ``.at[].set`` with a
static index, donation killed by same-statement rebinding, helper calls
acting as host barriers) so FP regressions break loudly here instead of
breaking the serving gate."""

from deepspeed_tpu.analysis import analyze_source


def _errors(src, rule=None):
    out = [f for f in analyze_source(src) if f.severity == "error"
           and not f.suppressed]
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


# ------------------------------------------------------ recompile-hazard
def test_recompile_item_in_jitted_fn_fires():
    src = (
        "import jax\n"
        "def f(x):\n"
        "    return x.item()\n"
        "g = jax.jit(f)\n")
    (f,) = _errors(src, "recompile-hazard")
    assert f.line == 3 and ".item()" in f.message


def test_recompile_branch_on_traced_fires_each_form():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, y):\n"
        "    if x:\n"
        "        return y\n"
        "    while not y:\n"
        "        pass\n"
        "    return int(x)\n")
    rules = {(f.line, f.rule) for f in _errors(src, "recompile-hazard")}
    assert (4, "recompile-hazard") in rules
    assert (6, "recompile-hazard") in rules
    assert (8, "recompile-hazard") in rules


def test_recompile_range_len_fires():
    src = (
        "import jax, functools\n"
        "@functools.partial(jax.jit, static_argnums=(1,))\n"
        "def f(x, n):\n"
        "    for i in range(len(x)):\n"
        "        pass\n"
        "    return x\n")
    (f,) = _errors(src, "recompile-hazard")
    assert f.line == 4


def test_recompile_static_argnums_and_shape_access_silent():
    # n is static (per static_argnums) and shape access is trace-time
    src = (
        "import jax, functools\n"
        "import jax.numpy as jnp\n"
        "@functools.partial(jax.jit, static_argnums=(1,))\n"
        "def f(x, n):\n"
        "    if n:\n"
        "        x = x + 1\n"
        "    if x.shape[0] > 2:\n"
        "        x = x * 2\n"
        "    return jnp.zeros(x.shape)\n")
    assert _errors(src) == []


def test_recompile_membership_and_compare_silent():
    # `if key not in cs` / comparisons over traced dicts are static —
    # the paged pool's _copy_page_body idiom
    src = (
        "import jax\n"
        "def body(cs, slot):\n"
        "    out = {}\n"
        "    for key in cs:\n"
        "        if key != 'index':\n"
        "            out[key] = cs[key]\n"
        "    return out\n"
        "wrapped = jax.jit(body, donate_argnums=(0,))\n")
    assert _errors(src) == []


def test_recompile_transitive_helper_and_self_method():
    # helpers called from jitted code run under the same trace — the
    # `scatter = self._scatter_cols` aliasing idiom
    src = (
        "import jax\n"
        "class P:\n"
        "    def bind(self):\n"
        "        def body(cs, w):\n"
        "            helper = self._helper\n"
        "            return helper(cs, w)\n"
        "        self._jit = jax.jit(body, donate_argnums=(0,))\n"
        "    def _helper(self, cs, w):\n"
        "        return bool(w)\n")
    (f,) = _errors(src, "recompile-hazard")
    assert f.func == "P._helper"


# ---------------------------------------------------- uncommitted-buffer
def test_uncommitted_self_assign_fires():
    src = (
        "import jax.numpy as jnp\n"
        "class Pool:\n"
        "    def __init__(self):\n"
        "        self.cache = jnp.zeros((4, 4))\n")
    (f,) = _errors(src, "uncommitted-buffer")
    assert f.line == 4 and "self.cache" in f.message


def test_uncommitted_via_local_var_fires():
    src = (
        "import jax.numpy as jnp\n"
        "class Pool:\n"
        "    def build(self):\n"
        "        buf = jnp.full((8,), 0)\n"
        "        self.table = buf\n")
    (f,) = _errors(src, "uncommitted-buffer")
    assert f.line == 5


def test_uncommitted_device_put_silent():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "class Pool:\n"
        "    def __init__(self, s):\n"
        "        self.cache = jax.device_put(jnp.zeros((4, 4)), s)\n"
        "        buf = jnp.ones((8,))\n"
        "        buf = jax.device_put(buf, s)\n"
        "        self.table = buf\n")
    assert _errors(src) == []


def test_uncommitted_local_only_and_inside_jit_silent():
    # a returned local (the _fresh_cache idiom) and allocations inside
    # a jitted function are both fine
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "class Pool:\n"
        "    def _fresh(self):\n"
        "        cs = {}\n"
        "        cs['index'] = jnp.zeros((4,), jnp.int32)\n"
        "        return cs\n"
        "    def _body(self, pool):\n"
        "        return jnp.zeros_like(pool)\n"
        "    def bind(self):\n"
        "        self._jit = jax.jit(self._body)\n")
    assert _errors(src) == []


# ---------------------------------------------------- donation-after-use
def test_donation_read_after_donating_call_fires():
    src = (
        "import jax\n"
        "class Pool:\n"
        "    def bind(self):\n"
        "        self._admit_jit = jax.jit(self._admit, donate_argnums=(0,))\n"
        "    def _admit(self, pool, pre):\n"
        "        return pool\n"
        "    def admit(self, pre):\n"
        "        out = self._admit_jit(self.cache, pre)\n"
        "        return self.cache['index']\n")
    (f,) = _errors(src, "donation-after-use")
    assert f.line == 9 and "self.cache" in f.message


def test_donation_same_statement_rebind_silent():
    # the engine idiom: the donated buffer is rebound from the call's
    # result in the same (or next) statement
    src = (
        "import jax\n"
        "class Pool:\n"
        "    def bind(self):\n"
        "        self._admit_jit = jax.jit(self._admit, donate_argnums=(0,))\n"
        "    def _admit(self, pool, pre):\n"
        "        return pool\n"
        "    def admit(self, pre):\n"
        "        self.cache = self._admit_jit(self.cache, pre)\n"
        "        return self.cache['index']\n")
    assert _errors(src) == []


def test_donation_fallback_map_cross_module():
    # call sites of wrappers defined in ANOTHER module gate through the
    # name-keyed fallback map (the engine calling _jit_decode)
    bad = (
        "class S:\n"
        "    def step(self, eng, tokens, pos):\n"
        "        logits, cache = eng._jit_decode(eng.params,\n"
        "                                        self.pool.cache, tokens,\n"
        "                                        pos)\n"
        "        stale = self.pool.cache['cache_store']\n"
        "        self.pool.cache = cache\n"
        "        return logits, stale\n")
    (f,) = _errors(bad, "donation-after-use")
    assert f.line == 6
    good = bad.replace("        stale = self.pool.cache['cache_store']\n",
                       "")
    assert _errors(good) == []


# -------------------------------------------------------- unsafe-scatter
def test_scatter_dynamic_index_without_mode_fires():
    src = (
        "def admit(pool, slot, length):\n"
        "    return pool.at[slot].set(length)\n")
    (f,) = _errors(src, "unsafe-scatter")
    assert f.line == 2 and "mode=" in f.message


def test_scatter_add_dynamic_fires():
    src = (
        "def bump(refs, pages):\n"
        "    return refs.at[pages].add(1)\n")
    assert len(_errors(src, "unsafe-scatter")) == 1


def test_scatter_explicit_mode_silent():
    src = (
        "def admit(pool, slot, length):\n"
        "    return pool.at[slot].set(length, mode='drop')\n")
    assert _errors(src) == []


def test_scatter_static_index_silent():
    src = (
        "def seed(pool, v):\n"
        "    a = pool.at[0].set(v)\n"
        "    b = pool.at[:, 2].set(v)\n"
        "    c = pool.at[-1].set(v)\n"
        "    return a, b, c\n")
    assert _errors(src) == []


# ---------------------------------------------------- hot-loop-host-sync
_HOT_PREAMBLE = (
    "import numpy as np\n"
    "import jax.numpy as jnp\n")


def test_hot_loop_sync_in_step_fires():
    src = _HOT_PREAMBLE + (
        "class Srv:\n"
        "    def step(self):\n"
        "        logits = self._jit_decode(self.params)\n"
        "        return float(logits)\n")
    (f,) = _errors(src, "hot-loop-host-sync")
    assert f.line == 6 and "float" in f.message


def test_hot_loop_sync_in_step_reachable_helper_fires():
    src = _HOT_PREAMBLE + (
        "class Srv:\n"
        "    def step(self):\n"
        "        return self._decode()\n"
        "    def _decode(self):\n"
        "        logits = self.pool.run_decode(1)\n"
        "        return np.asarray(logits)\n")
    (f,) = _errors(src, "hot-loop-host-sync")
    assert f.func == "Srv._decode"


def test_hot_loop_unreachable_method_silent():
    # same sync, but not reachable from step() — warmup/debug paths are
    # free to sync
    src = _HOT_PREAMBLE + (
        "class Srv:\n"
        "    def step(self):\n"
        "        return None\n"
        "    def warmup(self):\n"
        "        logits = self.pool.run_decode(1)\n"
        "        return np.asarray(logits)\n")
    assert _errors(src) == []


def test_hot_loop_host_data_and_helper_barrier_silent():
    # np over host data is fine, and a helper call is a host barrier:
    # its internal sync is charged once, not again at every caller
    src = _HOT_PREAMBLE + (
        "class Srv:\n"
        "    def step(self):\n"
        "        gaps = [1.0, 2.0]\n"
        "        p95 = float(np.percentile(np.asarray(gaps), 95))\n"
        "        logits = self._jit_decode(self.params)\n"
        "        tokens = self._sample(logits)\n"
        "        return int(tokens[0]) + p95\n")
    assert _errors(src) == []


def test_hot_loop_sink_result_is_host():
    # the np.asarray itself fires once; the host copy it returns is
    # then free to use
    src = _HOT_PREAMBLE + (
        "class Srv:\n"
        "    def step(self):\n"
        "        finite = np.asarray(self._jit_finite(self.logits))\n"
        "        return bool(finite[0])\n")
    errs = _errors(src, "hot-loop-host-sync")
    assert [f.line for f in errs] == [5]
