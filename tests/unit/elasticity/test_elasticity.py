"""Elastic-batch math — analog of reference
``tests/unit/elasticity/test_elastic.py``."""

import json

import pytest

from deepspeed_tpu.elasticity import (
    DSElasticAgent,
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityIncompatibleWorldSize,
    WorkerSpec,
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
)

BASE_CONFIG = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 10000,
        "micro_batch_sizes": [8, 12, 16, 17],
        "min_gpus": 32,
        "max_gpus": 1500,
        "min_time": 20,
        "version": 0.1,
    }
}


def test_basic_10k():
    final_batch_size, valid_gpus = compute_elastic_config(BASE_CONFIG)
    # every valid world size divides the batch through some micro batch
    for w in valid_gpus:
        assert any(final_batch_size % (m * w) == 0
                   for m in BASE_CONFIG["elasticity"]["micro_batch_sizes"]), \
            (final_batch_size, w)
    assert 32 <= min(valid_gpus)
    assert max(valid_gpus) <= 1500
    assert final_batch_size <= 10000


def test_deterministic():
    a = compute_elastic_config(BASE_CONFIG)
    b = compute_elastic_config(json.loads(json.dumps(BASE_CONFIG)))
    assert a == b


def test_world_size_validation():
    cfg = json.loads(json.dumps(BASE_CONFIG))
    _, valid = compute_elastic_config(cfg)
    ok = valid[0]
    compute_elastic_config(cfg, world_size=ok)  # no raise
    bad = max(valid) + 1
    while bad in valid:
        bad += 1
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(cfg, world_size=bad)


def test_disabled_raises():
    cfg = {"elasticity": {"enabled": False, "max_train_batch_size": 100,
                          "micro_batch_sizes": [2]}}
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(cfg)


def test_missing_block_raises():
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config({})


def test_train_batch_conflict_raises():
    cfg = json.loads(json.dumps(BASE_CONFIG))
    cfg["train_batch_size"] = 64
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(cfg)
    cfg["elasticity"]["ignore_non_elastic_batch_info"] = True
    compute_elastic_config(cfg)  # no raise


def test_invalid_config_values():
    with pytest.raises(ElasticityConfigError):
        ElasticityConfig({"enabled": True, "micro_batch_sizes": [2]})
    with pytest.raises(ElasticityConfigError):
        ElasticityConfig({"enabled": True, "max_train_batch_size": 4,
                          "micro_batch_sizes": [8]})
    with pytest.raises(ElasticityConfigError):
        ElasticityConfig({"enabled": True, "max_train_batch_size": 100,
                          "micro_batch_sizes": [0, 2]})


def test_v02_model_parallel():
    cfg = {
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 2048,
            "micro_batch_sizes": [2, 4],
            "min_gpus": 8,
            "max_gpus": 64,
            "version": 0.2,
            "num_gpus_per_node": 8,
            "model_parallel_size": 2,
        }
    }
    batch, valid, micro = compute_elastic_config(cfg, world_size=16,
                                                 return_microbatch=True)
    assert micro in (2, 4)
    # dp world = 16/2 = 8 must be able to consume the batch
    assert batch % micro == 0


def test_v01_rejects_model_parallel():
    cfg = json.loads(json.dumps(BASE_CONFIG))
    cfg["elasticity"]["model_parallel_size"] = 2
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(cfg)


def test_elasticity_enabled_helper():
    assert elasticity_enabled(BASE_CONFIG)
    assert not elasticity_enabled({})


def test_immutable_config_check(monkeypatch):
    block = BASE_CONFIG["elasticity"]
    monkeypatch.setenv("DEEPSPEED_ELASTICITY_CONFIG", json.dumps(block))
    ensure_immutable_elastic_config(block)  # same → ok
    changed = dict(block, max_train_batch_size=5000)
    with pytest.raises(ElasticityConfigError):
        ensure_immutable_elastic_config(changed)


def test_elastic_agent_restarts(tmp_path):
    """Worker fails twice then succeeds; agent must retry and exit 0."""
    import sys
    import textwrap

    marker = tmp_path / "attempts"
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        p = {str(marker)!r}
        n = int(open(p).read()) if os.path.exists(p) else 0
        open(p, "w").write(str(n + 1))
        sys.exit(0 if n >= 2 else 1)
    """))
    spec = WorkerSpec(entrypoint=[sys.executable, str(script)],
                      local_world_size=1, max_restarts=3,
                      monitor_interval=0.05)
    agent = DSElasticAgent(spec)
    assert agent.run() == 0
    assert int(marker.read_text()) == 3


def test_elastic_agent_exhausts_restarts(tmp_path):
    import sys

    spec = WorkerSpec(entrypoint=[sys.executable, "-c", "import sys; sys.exit(3)"],
                      local_world_size=1, max_restarts=1,
                      monitor_interval=0.05)
    agent = DSElasticAgent(spec)
    assert agent.run() == 3
    assert agent.restarts == 1
