"""Compression suite — analog of reference
``tests/unit/compression/test_compression.py``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.compression import (
    CompressionConfig,
    build_compression_transform,
    init_compression,
    quantize_activation,
    redundancy_clean,
    student_initialization,
)

WQ_CONFIG = {
    "weight_quantization": {
        "shared_parameters": {
            "enabled": True,
            "schedule_offset": 5,
            "quantize_groups": 1,
            "quantization_type": "symmetric",
            "rounding": "nearest",
        },
        "different_groups": {
            "wq1": {
                "params": {"start_bits": 12, "target_bits": 4,
                           "quantization_period": 5},
                "modules": ["linear_0"],
            }
        },
    }
}


def test_config_parses_reference_schema():
    cc = CompressionConfig(WQ_CONFIG)
    assert cc.enabled
    assert len(cc.groups) == 1
    g = cc.groups[0]
    assert g.technique == "weight_quantization"
    assert g.schedule_offset == 5
    assert g.matches("linear_0.kernel")
    assert not g.matches("head.kernel")


def test_weight_quantization_gated_by_schedule():
    _, transform = init_compression({"compression_training": WQ_CONFIG})
    params = {"linear_0": {"kernel": jnp.asarray(
        np.random.default_rng(0).standard_normal((8, 8)).astype(np.float32))},
        "head": {"kernel": jnp.ones((8, 8))}}
    before = transform(params, jnp.asarray(0))
    np.testing.assert_array_equal(np.asarray(before["linear_0"]["kernel"]),
                                  np.asarray(params["linear_0"]["kernel"]))
    after = transform(params, jnp.asarray(1000))
    # matched group quantized, unmatched untouched
    assert not np.allclose(np.asarray(after["linear_0"]["kernel"]),
                           np.asarray(params["linear_0"]["kernel"]))
    np.testing.assert_array_equal(np.asarray(after["head"]["kernel"]),
                                  np.asarray(params["head"]["kernel"]))
    # 4-bit symmetric → few distinct values
    u = np.unique(np.round(np.asarray(after["linear_0"]["kernel"]), 5))
    assert len(u) <= 16 + 1, len(u)


def test_sparse_and_row_pruning():
    cfg = {
        "sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0,
                                  "method": "l1"},
            "different_groups": {
                "sp1": {"params": {"dense_ratio": 0.5},
                        "modules": ["dense"]}},
        },
        "row_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {
                "rp1": {"params": {"dense_ratio": 0.5},
                        "modules": ["proj"]}},
        },
    }
    _, transform = init_compression(cfg)
    rng = np.random.default_rng(0)
    params = {"dense": {"kernel": jnp.asarray(
        rng.standard_normal((16, 16)).astype(np.float32))},
        "proj": {"kernel": jnp.asarray(
            rng.standard_normal((16, 16)).astype(np.float32))}}
    out = transform(params, jnp.asarray(10))
    sparse = np.asarray(out["dense"]["kernel"])
    assert 0.4 <= (sparse == 0).mean() <= 0.6, (sparse == 0).mean()
    rowpruned = np.asarray(out["proj"]["kernel"])
    zero_cols = (rowpruned == 0).all(axis=0)
    assert 0.4 <= zero_cols.mean() <= 0.6, zero_cols.mean()


def test_head_pruning():
    cfg = {
        "head_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {
                "hp1": {"params": {"dense_ratio": 0.5, "num_heads": 4},
                        "modules": ["attn_out"]}},
        },
    }
    _, transform = init_compression(cfg)
    rng = np.random.default_rng(0)
    params = {"attn_out": {"kernel": jnp.asarray(
        rng.standard_normal((16, 8)).astype(np.float32))}}
    out = transform(params, jnp.asarray(1))
    k = np.asarray(out["attn_out"]["kernel"])
    # 2 of 4 head slices (4 rows each) fully zeroed
    head_zero = [(k[h * 4:(h + 1) * 4] == 0).all() for h in range(4)]
    assert sum(head_zero) == 2, head_zero


def test_redundancy_clean():
    cc, _ = init_compression({"compression_training": WQ_CONFIG})
    params = {"linear_0": {"kernel": jnp.asarray(
        np.random.default_rng(0).standard_normal((8, 8)).astype(np.float32))}}
    cleaned = redundancy_clean(params, cc)
    u = np.unique(np.round(np.asarray(cleaned["linear_0"]["kernel"]), 5))
    assert len(u) <= 17


def test_activation_quantization():
    x = jnp.asarray(np.random.default_rng(0)
                    .standard_normal((4, 32)).astype(np.float32))
    q = quantize_activation(x, bits=8)
    assert float(jnp.max(jnp.abs(q - x))) < 0.05
    q4 = quantize_activation(x, bits=4, q_type="symmetric")
    assert float(jnp.mean((q4 - x) ** 2)) > float(jnp.mean((q - x) ** 2))


def test_student_initialization_layer_reduction():
    def layer(seed):
        return {"kernel": jnp.full((4, 4), float(seed))}

    teacher = {"encoder": {"layer": {str(i): layer(i) for i in range(6)}},
               "pooler": {"kernel": jnp.full((4, 4), 99.0)}}
    student = {"encoder": {"layer": {str(i): layer(0) for i in range(3)}},
               "pooler": {"kernel": jnp.zeros((4, 4))}}
    out = student_initialization(student, teacher, {
        "layer_reduction": {"enabled": True, "teacher_layer": [1, 3, 5]}})
    assert float(out["encoder"]["layer"]["0"]["kernel"][0, 0]) == 1.0
    assert float(out["encoder"]["layer"]["1"]["kernel"][0, 0]) == 3.0
    assert float(out["encoder"]["layer"]["2"]["kernel"][0, 0]) == 5.0
    assert float(out["pooler"]["kernel"][0, 0]) == 99.0


def test_compressed_layers_forward():
    from deepspeed_tpu.compression import (
        EmbeddingCompress,
        LinearLayerCompress,
    )

    lin = LinearLayerCompress(features=8, act_bits=8, weight_bits=8)
    x = jnp.ones((2, 4))
    params = lin.init(jax.random.PRNGKey(0), x)
    y = lin.apply(params, x)
    assert y.shape == (2, 8)

    emb = EmbeddingCompress(num_embeddings=10, features=4, weight_bits=8)
    ids = jnp.asarray([[1, 2], [3, 4]])
    params = emb.init(jax.random.PRNGKey(0), ids)
    out = emb.apply(params, ids)
    assert out.shape == (2, 2, 4)


def test_engine_compression_training():
    """End-to-end: engine applies weight quantization after the offset."""
    from tests.unit.simple_model import SimpleModel, random_batch

    config = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "compression_training": {
            "weight_quantization": {
                "shared_parameters": {"enabled": True, "schedule_offset": 2,
                                      "quantization_type": "symmetric"},
                "different_groups": {
                    "wq1": {"params": {"start_bits": 8, "target_bits": 4,
                                       "quantization_period": 1},
                            "modules": ["linear_0"]}},
            }
        },
        "steps_per_print": 1000,
    }
    engine, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                    config=config)
    b = random_batch(engine.train_batch_size())
    for _ in range(8):
        engine.train_batch(batch=b)
    k = np.asarray(jax.device_get(
        engine.state["params"]["linear_0"]["kernel"]))
    u = np.unique(np.round(k, 4))
    assert len(u) <= 33, len(u)  # 4-bit quantized grid (plus blend residue)
    assert engine.compression_scheduler.active_groups()


# ---------------------------------------------------------------------------
# round 2: conv/BN layers, TP compressed linears, physical dim reduction
# ---------------------------------------------------------------------------
def test_conv_layer_compress_forward_and_pruning():
    from deepspeed_tpu.compression import ConvLayerCompress

    conv = ConvLayerCompress(features=8, kernel_size=(3, 3), act_bits=8,
                             weight_bits=8, sparse_dense_ratio=0.5,
                             channel_dense_ratio=0.5)
    x = jnp.asarray(np.random.default_rng(0)
                    .standard_normal((2, 8, 8, 3)).astype(np.float32))
    params = conv.init(jax.random.PRNGKey(0), x)
    y = conv.apply(params, x)
    assert y.shape == (2, 8, 8, 8)
    # channel pruning zeroes half of the output channels entirely
    dead = (np.asarray(y) == 0).all(axis=(0, 1, 2))
    assert dead.sum() == 4, dead


def test_bn_compress_masks_channels():
    from deepspeed_tpu.compression import BNCompress

    bn = BNCompress(use_running_average=False)
    x = jnp.asarray(np.random.default_rng(1)
                    .standard_normal((2, 4, 4, 6)).astype(np.float32))
    mask = jnp.asarray([1.0, 0.0, 1.0, 0.0, 1.0, 1.0])
    variables = bn.init(jax.random.PRNGKey(0), x, mask)
    y, _ = bn.apply(variables, x, mask, mutable=["batch_stats"])
    assert (np.asarray(y)[..., 1] == 0).all()
    assert not (np.asarray(y)[..., 0] == 0).all()


def test_tp_compressed_linears_on_mesh(eight_device_mesh):
    from deepspeed_tpu.compression import (
        ColumnParallelLinearCompress,
        RowParallelLinearCompress,
    )

    class TpMlp(__import__("flax").linen.Module):
        @__import__("flax").linen.compact
        def __call__(self, x):
            x = ColumnParallelLinearCompress(
                features=16, weight_bits=8, name="col_parallel_fc")(x)
            x = jax.nn.relu(x)
            return RowParallelLinearCompress(
                features=4, weight_bits=8, name="row_parallel_proj")(x)

    mlp = TpMlp()
    x = jnp.ones((2, 8))
    params = mlp.init(jax.random.PRNGKey(0), x)
    y = jax.jit(lambda p, v: mlp.apply(p, v))(params, x)
    assert y.shape == (2, 4)
    assert np.isfinite(np.asarray(y)).all()


def test_compression_tp_rules_match_param_names():
    import re

    from deepspeed_tpu.compression import compression_tp_rules

    rules = dict((pat, spec) for pat, spec in compression_tp_rules())
    assert any(re.search(p, "col_parallel_fc/kernel") for p in rules)
    assert any(re.search(p, "row_parallel_proj/kernel") for p in rules)


def test_shrink_params_row_pruning_parity():
    """Physical dim reduction (reference fix_compression dim_reduction=True):
    the compacted small MLP reproduces the kept-unit computation exactly."""
    from deepspeed_tpu.compression import CompressionConfig, shrink_params

    rng = np.random.default_rng(0)
    k1 = rng.standard_normal((8, 16)).astype(np.float32)
    b1 = rng.standard_normal(16).astype(np.float32)
    k2 = rng.standard_normal((16, 4)).astype(np.float32)
    params = {"fc1": {"kernel": jnp.asarray(k1), "bias": jnp.asarray(b1)},
              "fc2": {"kernel": jnp.asarray(k2)}}
    cc = CompressionConfig({
        "row_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {
                "rp1": {"params": {"dense_ratio": 0.5},
                        "modules": ["fc1"]}}},
    })
    small = shrink_params(params, cc, couplings={"fc1.kernel": ["fc2.kernel"]})
    assert np.asarray(small["fc1"]["kernel"]).shape == (8, 8)
    assert np.asarray(small["fc1"]["bias"]).shape == (8,)
    assert np.asarray(small["fc2"]["kernel"]).shape == (8, 4)

    # kept indices = the 8 largest-L1 output columns of k1
    scores = np.abs(k1).sum(axis=0)
    kept = np.sort(np.argsort(scores)[-8:])
    x = rng.standard_normal((3, 8)).astype(np.float32)
    ref = np.maximum(x @ k1[:, kept] + b1[kept], 0) @ k2[kept]
    got = np.maximum(
        x @ np.asarray(small["fc1"]["kernel"]) + np.asarray(small["fc1"]["bias"]),
        0) @ np.asarray(small["fc2"]["kernel"])
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_shrink_params_head_pruning():
    from deepspeed_tpu.compression import CompressionConfig, shrink_params

    rng = np.random.default_rng(2)
    # 4 heads x head_dim 4 = 16; output proj (16, 8); value proj (8, 16)
    params = {"attn_out": {"kernel": jnp.asarray(
        rng.standard_normal((16, 8)).astype(np.float32))},
        "v_proj": {"kernel": jnp.asarray(
            rng.standard_normal((8, 16)).astype(np.float32)),
            "bias": jnp.asarray(rng.standard_normal(16).astype(np.float32))}}
    cc = CompressionConfig({
        "head_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {
                "hp1": {"params": {"dense_ratio": 0.5, "num_heads": 4},
                        "modules": ["attn_out"]}}},
    })
    small = shrink_params(params, cc,
                          couplings={"attn_out.kernel": ["v_proj.kernel"]})
    # 2 of 4 heads kept → 8 input units on the out proj, 8 outputs on v_proj
    assert np.asarray(small["attn_out"]["kernel"]).shape == (8, 8)
    assert np.asarray(small["v_proj"]["kernel"]).shape == (8, 8)
    assert np.asarray(small["v_proj"]["bias"]).shape == (8,)
