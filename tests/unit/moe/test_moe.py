"""MoE tests (analog of reference tests/unit/moe/test_moe.py)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.moe import MoE, moe_sharding_rules, top1gating, top2gating
from deepspeed_tpu.moe.sharded_moe import (combine_indexed, combine_output,
                                           dispatch_indexed, expert_counts,
                                           gate_and_dispatch, gate_decisions)
from deepspeed_tpu.parallel import initialize_mesh
from deepspeed_tpu.runtime.zero.policy import ShardingRules
from tests.unit.simple_model import base_config


def test_top1_capacity_and_shapes():
    logits = jax.random.normal(jax.random.PRNGKey(0), (64, 8))
    aux, combine, dispatch, cap = top1gating(logits, capacity_factor=1.0,
                                             min_capacity=4)
    assert combine.shape == (64, 8, cap)
    assert cap == 8  # 64 tokens / 8 experts * 1.0
    # every kept token has exactly one (expert, slot)
    assert (np.asarray(dispatch).sum(axis=(1, 2)) <= 1).all()
    assert float(aux) > 0


def test_top1_no_drop():
    logits = jax.random.normal(jax.random.PRNGKey(0), (32, 4))
    _, combine, dispatch, cap = top1gating(logits, drop_tokens=False)
    assert cap == 32
    assert (np.asarray(dispatch).sum(axis=(1, 2)) == 1).all()  # nothing dropped


def test_top2_two_experts_per_token():
    logits = jax.random.normal(jax.random.PRNGKey(1), (64, 8))
    aux, combine, dispatch, cap = top2gating(logits, capacity_factor=2.0,
                                             drop_tokens=False)
    counts = np.asarray(dispatch).sum(axis=(1, 2))
    assert (counts <= 2).all() and counts.max() == 2
    # combine weights of a token sum to ~1 (renormalized top-2)
    sums = np.asarray(combine).sum(axis=(1, 2))
    kept = counts == 2
    np.testing.assert_allclose(sums[kept], 1.0, rtol=1e-5)


def test_dispatch_combine_roundtrip_identity_experts():
    """With identity experts and no drop, combine(dispatch(x)) ≈ x * gate_sum."""
    rng = jax.random.PRNGKey(2)
    tokens = jax.random.normal(rng, (32, 16))
    logits = jax.random.normal(jax.random.PRNGKey(3), (32, 4))
    _, dispatched, combine = gate_and_dispatch(tokens, logits, k=2,
                                               drop_tokens=False)
    out = combine_output(dispatched, combine)
    # top-2 combine weights sum to 1 → reconstruction equals original tokens
    np.testing.assert_allclose(np.asarray(out), np.asarray(tokens), rtol=1e-4,
                               atol=1e-5)


@pytest.mark.parametrize("k", [1, 2])
@pytest.mark.parametrize("drop", [True, False])
def test_indexed_dispatch_matches_einsum(k, drop):
    """Index (scatter/gather) dispatch == dense einsum dispatch, fwd + bwd,
    from the SAME routing decisions."""
    E, S, M = 4, 64, 16
    tokens = jax.random.normal(jax.random.PRNGKey(0), (S, M))
    logits = jax.random.normal(jax.random.PRNGKey(1), (S, E))
    dec = gate_decisions(logits, k=k, capacity_factor=1.0, drop_tokens=drop)

    from deepspeed_tpu.moe.sharded_moe import _densify

    def einsum_path(t):
        combine, dispatch = _densify(dec, E, t.dtype)
        dispatched = jnp.einsum("sec,sm->ecm", dispatch.astype(t.dtype), t)
        # "experts": a fixed elementwise transform so output depends on
        # routing but not extra params
        return combine_output(dispatched * 2.0 + 1.0, combine)

    def index_path(t):
        dispatched = dispatch_indexed(t, dec, E)
        return combine_indexed(dispatched * 2.0 + 1.0, dec)

    out_e = einsum_path(tokens)
    out_i = index_path(tokens)
    np.testing.assert_allclose(np.asarray(out_i), np.asarray(out_e),
                               rtol=1e-5, atol=1e-5)

    g_e = jax.grad(lambda t: jnp.sum(jnp.sin(einsum_path(t))))(tokens)
    g_i = jax.grad(lambda t: jnp.sum(jnp.sin(index_path(t))))(tokens)
    np.testing.assert_allclose(np.asarray(g_i), np.asarray(g_e),
                               rtol=1e-5, atol=1e-5)

    # exp_counts parity with the dense dispatch mask
    combine, dispatch = _densify(dec, E, tokens.dtype)
    np.testing.assert_array_equal(
        np.asarray(expert_counts(dec, E)),
        np.asarray(jnp.sum(dispatch, axis=(0, 2))))


@pytest.mark.parametrize("k", [1, 2])
def test_moe_layer_dispatch_modes_agree(k):
    """Full MoE layer: dispatch_mode='index' == 'einsum' (same params/rng)."""
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 16))

    def build(mode):
        return MoE(hidden_size=16, num_experts=4, k=k, capacity_factor=2.0,
                   drop_tokens=True, dispatch_mode=mode)

    params = build("einsum").init(jax.random.PRNGKey(1), x)
    out_e, aux_e, cnt_e = build("einsum").apply(params, x)
    out_i, aux_i, cnt_i = build("index").apply(params, x)
    np.testing.assert_allclose(np.asarray(out_i), np.asarray(out_e),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux_i), float(aux_e), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(cnt_i), np.asarray(cnt_e))

    def loss(p, mode):
        out, aux, _ = build(mode).apply(p, x)
        return jnp.sum(out ** 2) + aux

    g_e = jax.grad(loss)(params, "einsum")
    g_i = jax.grad(loss)(params, "index")
    for a, b in zip(jax.tree_util.tree_leaves(g_e),
                    jax.tree_util.tree_leaves(g_i)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


class MoEModel(nn.Module):
    """Tiny LM-ish model with a MoE layer; returns (loss, aux)."""

    hidden: int = 16
    num_experts: int = 4
    k: int = 1
    use_residual: bool = False
    dispatch_mode: str = "auto"

    @nn.compact
    def __call__(self, batch, deterministic: bool = False):
        x = batch["x"]
        x = nn.Dense(self.hidden, name="in_proj")(x)
        moe_out, aux, _ = MoE(hidden_size=self.hidden, num_experts=self.num_experts,
                              k=self.k, capacity_factor=2.0, drop_tokens=False,
                              use_residual=self.use_residual,
                              dispatch_mode=self.dispatch_mode,
                              name="moe")(x, deterministic=deterministic)
        out = nn.Dense(1, name="head")(moe_out)
        loss = jnp.mean((out.squeeze(-1) - batch["y"]) ** 2)
        return loss, 0.01 * aux


@pytest.mark.parametrize("k", [1, 2])
def test_moe_model_trains(k):
    model = MoEModel(k=k)
    rules = ShardingRules(moe_sharding_rules())
    engine, _, _, _ = ds.initialize(model=model, config=base_config(micro=2),
                                    sharding_rules=rules)
    rng = np.random.default_rng(0)
    batch = {"x": rng.normal(size=(16, 8, 16)).astype(np.float32),
             "y": rng.normal(size=(16, 8)).astype(np.float32)}
    l0 = float(engine.train_batch(batch=batch))
    for _ in range(8):
        loss = engine.train_batch(batch=batch)
    assert float(loss) < l0


@pytest.mark.parametrize("k,resolved", [(1, "einsum"), (2, "index")])
def test_auto_dispatch_mode_resolves_per_k(k, resolved):
    """'auto' = einsum for k=1, index for k>=2 (the measured policy);
    the output must equal the explicitly-selected form bitwise."""
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 16))

    def build(mode):
        return MoE(hidden_size=16, num_experts=4, k=k, capacity_factor=2.0,
                   dispatch_mode=mode)

    params = build("auto").init(jax.random.PRNGKey(1), x)
    out_a, aux_a, cnt_a = build("auto").apply(params, x)
    out_r, aux_r, cnt_r = build(resolved).apply(params, x)
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_r))
    np.testing.assert_array_equal(np.asarray(cnt_a), np.asarray(cnt_r))


def test_auto_dispatch_forces_index_above_dense_size_threshold():
    """At long S the dense (S,E,C) form is quadratic in S — 'auto' must
    fall back to index even at k=1 (threshold shrunk to make it bite)."""
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 16))  # S=32

    def build(mode, thresh):
        return MoE(hidden_size=16, num_experts=4, k=1, capacity_factor=2.0,
                   dispatch_mode=mode, auto_index_threshold=thresh)

    # S*E*C = 32*4*16 = 2048 dense elements; threshold below that → index
    params = build("auto", 2047).init(jax.random.PRNGKey(1), x)
    out_a, _, cnt_a = build("auto", 2047).apply(params, x)
    out_i, _, cnt_i = build("index", 2047).apply(params, x)
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_i))
    np.testing.assert_array_equal(np.asarray(cnt_a), np.asarray(cnt_i))


@pytest.mark.parametrize("mode", ["index", "einsum"])
def test_used_token_masks_padding_out_of_routing(mode):
    """Reference MoE.forward(used_token) (layer.py:100, sharded_moe.py:202):
    masked tokens must get zero MoE output and not occupy expert capacity."""
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    used = np.ones((2, 8), np.float32)
    used[:, 4:] = 0.0  # second half of every row is padding
    # apply() below runs deterministic (eval) mode → eval_capacity_factor
    moe = MoE(hidden_size=16, num_experts=4, k=1, capacity_factor=2.0,
              eval_capacity_factor=2.0, dispatch_mode=mode)
    params = moe.init(jax.random.PRNGKey(1), x)
    out_m, aux_m, cnt_m = moe.apply(params, x,
                                    used_token=jnp.asarray(used))
    out_f, aux_f, cnt_f = moe.apply(params, x)

    # padding rows produce exactly zero expert output
    np.testing.assert_array_equal(np.asarray(out_m)[:, 4:], 0.0)
    # real rows route identically to the unmasked case (capacity 2.0 is
    # ample, so no displacement happens here)
    np.testing.assert_allclose(np.asarray(out_m)[:, :4],
                               np.asarray(out_f)[:, :4],
                               rtol=1e-5, atol=1e-6)
    assert int(np.asarray(cnt_m).sum()) == 8  # only real tokens counted
    assert float(aux_m) != float(aux_f)  # padding left the balance stats


def test_residual_moe_blends_dense_and_expert_paths():
    """PR-MoE (use_residual, arXiv:2201.05596; reference layer.py:77,116):
    out = coef0 * moe_out + coef1 * dense_mlp(x) with a learned per-token
    softmax coefficient."""
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 16))
    moe = MoE(hidden_size=16, num_experts=4, k=1, capacity_factor=2.0,
              use_residual=True)
    params = moe.init(jax.random.PRNGKey(1), x)
    p = params["params"]
    assert "residual_mlp" in p and "coefficient" in p
    assert p["coefficient"]["kernel"].shape == (16, 2)

    out, aux, _ = moe.apply(params, x)
    assert out.shape == x.shape and np.isfinite(np.asarray(out)).all()

    # reconstruct the blend from the submodule outputs: must match exactly
    base = MoE(hidden_size=16, num_experts=4, k=1, capacity_factor=2.0,
               use_residual=False)
    base_params = {"params": {k: v for k, v in p.items()
                              if k not in ("residual_mlp", "coefficient")}}
    moe_out, _, _ = base.apply(base_params, x)
    tokens = x.reshape(-1, 16)
    from deepspeed_tpu.moe.layer import ExpertMLP
    mlp_out = ExpertMLP(hidden_size=16, intermediate_size=64).apply(
        {"params": p["residual_mlp"]}, tokens)
    coef = jax.nn.softmax(
        tokens @ p["coefficient"]["kernel"] + p["coefficient"]["bias"],
        axis=-1)
    expect = (moe_out.reshape(-1, 16) * coef[:, 0:1]
              + mlp_out * coef[:, 1:2]).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=1e-5, atol=1e-6)


def test_residual_moe_model_trains():
    model = MoEModel(k=1, use_residual=True)
    rules = ShardingRules(moe_sharding_rules())
    engine, _, _, _ = ds.initialize(model=model, config=base_config(micro=2),
                                    sharding_rules=rules)
    rng = np.random.default_rng(0)
    batch = {"x": rng.normal(size=(16, 8, 16)).astype(np.float32),
             "y": rng.normal(size=(16, 8)).astype(np.float32)}
    l0 = float(engine.train_batch(batch=batch))
    for _ in range(8):
        loss = engine.train_batch(batch=batch)
    assert float(loss) < l0


def test_index_dispatch_emits_expert_all_to_all():
    """The scatter/gather dispatch must still hand XLA a tensor whose
    expert dim moves onto the expert axis — the compiled EP program needs
    the all-to-all (or equivalent collective-permute pair) the reference
    issues explicitly (_AllToAll, sharded_moe.py:90)."""
    mesh = initialize_mesh(data=2, expert=4)
    model = MoEModel(num_experts=4, dispatch_mode="index")
    rules = ShardingRules(moe_sharding_rules())
    engine, _, _, _ = ds.initialize(model=model, config=base_config(micro=2),
                                    sharding_rules=rules, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"x": rng.normal(size=(16, 8, 16)).astype(np.float32),
             "y": rng.normal(size=(16, 8)).astype(np.float32)}
    stacked = engine._stack_micro_batches(batch)
    if engine.state is None:
        first = jax.tree_util.tree_map(lambda x: x[0], stacked)
        engine._build_state(engine._init_params_from_batch(first))
    hlo = engine._jit_train_batch.lower(engine.state, stacked) \
        .compile().as_text()
    assert ("all-to-all" in hlo) or ("collective-permute" in hlo), \
        "no cross-expert collective in the compiled EP step"


def test_moe_expert_parallel_mesh():
    """MoE over a mesh with a real expert axis: ep=4, dp=2."""
    mesh = initialize_mesh(data=2, expert=4)
    model = MoEModel(num_experts=4)
    rules = ShardingRules(moe_sharding_rules())
    engine, _, _, _ = ds.initialize(model=model, config=base_config(micro=2),
                                    sharding_rules=rules, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"x": rng.normal(size=(16, 8, 16)).astype(np.float32),
             "y": rng.normal(size=(16, 8)).astype(np.float32)}
    loss = engine.train_batch(batch=batch)
    assert np.isfinite(float(loss))
    # expert params sharded over expert axis
    flat = jax.tree_util.tree_leaves_with_path(engine.state["params"])
    expert_kernels = [leaf for path, leaf in flat
                      if "experts" in "/".join(str(p) for p in path)
                      and leaf.ndim == 3]
    assert expert_kernels, "no stacked expert params found"
    for leaf in expert_kernels:
        shard = leaf.sharding.shard_shape(leaf.shape)
        assert shard[0] == leaf.shape[0] // 4, "expert dim not sharded over ep axis"
