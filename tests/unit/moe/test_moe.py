"""MoE tests (analog of reference tests/unit/moe/test_moe.py)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.moe import MoE, moe_sharding_rules, top1gating, top2gating
from deepspeed_tpu.moe.sharded_moe import combine_output, gate_and_dispatch
from deepspeed_tpu.parallel import initialize_mesh
from deepspeed_tpu.runtime.zero.policy import ShardingRules
from tests.unit.simple_model import base_config


def test_top1_capacity_and_shapes():
    logits = jax.random.normal(jax.random.PRNGKey(0), (64, 8))
    aux, combine, dispatch, cap = top1gating(logits, capacity_factor=1.0,
                                             min_capacity=4)
    assert combine.shape == (64, 8, cap)
    assert cap == 8  # 64 tokens / 8 experts * 1.0
    # every kept token has exactly one (expert, slot)
    assert (np.asarray(dispatch).sum(axis=(1, 2)) <= 1).all()
    assert float(aux) > 0


def test_top1_no_drop():
    logits = jax.random.normal(jax.random.PRNGKey(0), (32, 4))
    _, combine, dispatch, cap = top1gating(logits, drop_tokens=False)
    assert cap == 32
    assert (np.asarray(dispatch).sum(axis=(1, 2)) == 1).all()  # nothing dropped


def test_top2_two_experts_per_token():
    logits = jax.random.normal(jax.random.PRNGKey(1), (64, 8))
    aux, combine, dispatch, cap = top2gating(logits, capacity_factor=2.0,
                                             drop_tokens=False)
    counts = np.asarray(dispatch).sum(axis=(1, 2))
    assert (counts <= 2).all() and counts.max() == 2
    # combine weights of a token sum to ~1 (renormalized top-2)
    sums = np.asarray(combine).sum(axis=(1, 2))
    kept = counts == 2
    np.testing.assert_allclose(sums[kept], 1.0, rtol=1e-5)


def test_dispatch_combine_roundtrip_identity_experts():
    """With identity experts and no drop, combine(dispatch(x)) ≈ x * gate_sum."""
    rng = jax.random.PRNGKey(2)
    tokens = jax.random.normal(rng, (32, 16))
    logits = jax.random.normal(jax.random.PRNGKey(3), (32, 4))
    _, dispatched, combine = gate_and_dispatch(tokens, logits, k=2,
                                               drop_tokens=False)
    out = combine_output(dispatched, combine)
    # top-2 combine weights sum to 1 → reconstruction equals original tokens
    np.testing.assert_allclose(np.asarray(out), np.asarray(tokens), rtol=1e-4,
                               atol=1e-5)


class MoEModel(nn.Module):
    """Tiny LM-ish model with a MoE layer; returns (loss, aux)."""

    hidden: int = 16
    num_experts: int = 4
    k: int = 1

    @nn.compact
    def __call__(self, batch, deterministic: bool = False):
        x = batch["x"]
        x = nn.Dense(self.hidden, name="in_proj")(x)
        moe_out, aux, _ = MoE(hidden_size=self.hidden, num_experts=self.num_experts,
                              k=self.k, capacity_factor=2.0, drop_tokens=False,
                              name="moe")(x, deterministic=deterministic)
        out = nn.Dense(1, name="head")(moe_out)
        loss = jnp.mean((out.squeeze(-1) - batch["y"]) ** 2)
        return loss, 0.01 * aux


@pytest.mark.parametrize("k", [1, 2])
def test_moe_model_trains(k):
    model = MoEModel(k=k)
    rules = ShardingRules(moe_sharding_rules())
    engine, _, _, _ = ds.initialize(model=model, config=base_config(micro=2),
                                    sharding_rules=rules)
    rng = np.random.default_rng(0)
    batch = {"x": rng.normal(size=(16, 8, 16)).astype(np.float32),
             "y": rng.normal(size=(16, 8)).astype(np.float32)}
    l0 = float(engine.train_batch(batch=batch))
    for _ in range(8):
        loss = engine.train_batch(batch=batch)
    assert float(loss) < l0


def test_moe_expert_parallel_mesh():
    """MoE over a mesh with a real expert axis: ep=4, dp=2."""
    mesh = initialize_mesh(data=2, expert=4)
    model = MoEModel(num_experts=4)
    rules = ShardingRules(moe_sharding_rules())
    engine, _, _, _ = ds.initialize(model=model, config=base_config(micro=2),
                                    sharding_rules=rules, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"x": rng.normal(size=(16, 8, 16)).astype(np.float32),
             "y": rng.normal(size=(16, 8)).astype(np.float32)}
    loss = engine.train_batch(batch=batch)
    assert np.isfinite(float(loss))
    # expert params sharded over expert axis
    flat = jax.tree_util.tree_leaves_with_path(engine.state["params"])
    expert_kernels = [leaf for path, leaf in flat
                      if "experts" in "/".join(str(p) for p in path)
                      and leaf.ndim == 3]
    assert expert_kernels, "no stacked expert params found"
    for leaf in expert_kernels:
        shard = leaf.sharding.shard_shape(leaf.shape)
        assert shard[0] == leaf.shape[0] // 4, "expert dim not sharded over ep axis"
