"""Hybrid engine (RLHF mode switching) — analog of reference
``tests/hybrid_engine/``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds


def _make_hybrid_engine():
    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )

    cfg = TransformerConfig(vocab_size=128, n_layer=2, n_head=2, n_embd=32,
                            max_seq_len=64)
    model = TransformerLM(cfg)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "hybrid_engine": {"enabled": True},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    return engine, cfg


def _batch(engine, cfg, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(
        0, cfg.vocab_size, (engine.train_batch_size(), seq)).astype(np.int32)}


def test_dispatch_and_train_generate_cycle():
    from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine

    engine, cfg = _make_hybrid_engine()
    assert isinstance(engine, DeepSpeedHybridEngine)
    b = _batch(engine, cfg)
    l0 = float(engine.train_batch(batch=b))

    prompt = np.asarray([[5, 6, 7, 8]], dtype=np.int32)
    out1 = np.asarray(engine.generate(prompt, max_new_tokens=4, greedy=True))
    assert out1.shape == (1, 8)

    # params advance → generation output may change, engine must refresh
    for _ in range(3):
        engine.train_batch(batch=b)
    v1 = engine._inference_param_version
    out2 = np.asarray(engine.generate(prompt, max_new_tokens=4, greedy=True))
    assert engine._inference_param_version > v1
    assert out2.shape == (1, 8)


def test_lora_fuse_unfuse_roundtrip():
    from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine

    engine, cfg = _make_hybrid_engine()
    rng = np.random.default_rng(0)
    params = {
        "proj": {
            "kernel": jnp.asarray(rng.standard_normal((8, 8)).astype(np.float32)),
            "lora_a": jnp.asarray(rng.standard_normal((8, 2)).astype(np.float32)),
            "lora_b": jnp.asarray(rng.standard_normal((2, 8)).astype(np.float32)),
        },
        "plain": {"kernel": jnp.ones((4, 4))},
    }
    fused = engine.fuse_lora_weight(params)
    expect = np.asarray(params["proj"]["kernel"]) + \
        np.asarray(params["proj"]["lora_a"]) @ \
        np.asarray(params["proj"]["lora_b"])
    np.testing.assert_allclose(np.asarray(fused["proj"]["kernel"]), expect,
                               rtol=1e-5)
    # lora_a zeroed so a LoRA-aware forward doesn't double-count
    assert (np.asarray(fused["proj"]["lora_a"]) == 0).all()
    np.testing.assert_array_equal(np.asarray(fused["plain"]["kernel"]),
                                  np.asarray(params["plain"]["kernel"]))
    # training params untouched (functional fuse)
    assert not (np.asarray(params["proj"]["lora_a"]) == 0).all()
    # unfuse inverts an in-place-style fuse (lora factors intact)
    manual_fused = {"proj": dict(params["proj"],
                                 kernel=jnp.asarray(expect)),
                    "plain": params["plain"]}
    unfused = engine.unfuse_lora_weight(manual_fused)
    np.testing.assert_allclose(np.asarray(unfused["proj"]["kernel"]),
                               np.asarray(params["proj"]["kernel"]),
                               rtol=1e-4, atol=1e-5)


def test_eval_train_mode_flip():
    engine, cfg = _make_hybrid_engine()
    engine.eval()
    assert engine._in_eval
    engine.train()
    assert not engine._in_eval
