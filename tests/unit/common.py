"""Multi-process distributed test harness (SURVEY §4 "core pattern").

The reference's ``DistributedExec`` (tests/unit/common.py:90) forks N
processes that rendezvous through torch.distributed before each test body.
The TPU translation: N REAL localhost processes, each forced onto the CPU
backend, rendezvousing through ``deepspeed_tpu.init_distributed`` →
``jax.distributed.initialize`` (Gloo CPU collectives), so cross-process
collective plumbing — coordinator discovery, device federation (one CPU
device per process), global-mesh construction — is genuinely exercised,
unlike the single-process virtual-mesh tests.

Usage: define a module-level worker ``def _my_worker(rank, world): ...`` in
the test file and call ``run_distributed(_my_worker, world_size=2)``.
Workers import the test file by path (no pickling), run the body, and exit
non-zero on any exception; the parent enforces a hang watchdog and reprints
worker logs on failure.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))

_BOOTSTRAP = r"""
import sys, os
path, fn_name, rank, world, port, payload = sys.argv[1:7]
os.environ["RANK"] = rank
os.environ["WORLD_SIZE"] = world
os.environ["MASTER_ADDR"] = "127.0.0.1"
os.environ["MASTER_PORT"] = port
import jax
jax.config.update("jax_platforms", "cpu")  # before ANY backend use
import deepspeed_tpu as ds
ds.init_distributed()
import importlib.util
spec = importlib.util.spec_from_file_location("_dist_test_module", path)
mod = importlib.util.module_from_spec(spec)
sys.modules["_dist_test_module"] = mod
spec.loader.exec_module(mod)
fn = getattr(mod, fn_name)
if payload == "-":
    fn(int(rank), int(world))
else:
    fn(int(rank), int(world), payload)
"""


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_distributed(fn, world_size: int = 2, timeout: float = 300.0,
                    payload: str | None = None, env: dict | None = None):
    """Run ``fn(rank, world[, payload])`` in ``world_size`` rendezvoused
    localhost processes. ``fn`` must be module-level in the calling test
    file. ``payload`` (optional string, e.g. a tmpdir) is forwarded to every
    worker. Raises on non-zero exit or watchdog timeout, with worker logs.
    """
    path = os.path.abspath(sys.modules[fn.__module__].__file__)
    port = free_port()
    worker_env = dict(os.environ)
    worker_env["PYTHONPATH"] = REPO_ROOT + os.pathsep + \
        worker_env.get("PYTHONPATH", "")
    # the virtual-mesh conftest env must not leak into the real
    # multi-process rendezvous (each worker contributes its own device)
    worker_env.pop("XLA_FLAGS", None)
    worker_env.update(env or {})

    logs, procs = [], []
    for rank in range(world_size):
        log = tempfile.NamedTemporaryFile(
            mode="w+", suffix=f".rank{rank}.log", delete=False)
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _BOOTSTRAP, path, fn.__name__,
             str(rank), str(world_size), str(port),
             payload if payload is not None else "-"],
            stdout=log, stderr=subprocess.STDOUT, env=worker_env,
            cwd=REPO_ROOT))

    deadline = time.monotonic() + timeout
    try:
        rcs = []
        for p in procs:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"distributed test hang: {fn.__name__} exceeded "
                    f"{timeout}s (watchdog)")
            rcs.append(p.wait(timeout=remaining))
    except (TimeoutError, subprocess.TimeoutExpired) as e:
        for p in procs:
            if p.poll() is None:
                p.kill()
        raise TimeoutError(_format_failure(fn, logs, "WATCHDOG")) from e
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    if any(rc != 0 for rc in rcs):
        raise AssertionError(_format_failure(fn, logs, rcs))


def _format_failure(fn, logs, rcs) -> str:
    out = [f"distributed worker failure in {fn.__name__}: rcs={rcs}"]
    for i, log in enumerate(logs):
        log.flush()
        log.seek(0)
        tail = log.read()[-4000:]
        out.append(f"--- rank {i} log ---\n{tail}")
    return "\n".join(out)
