"""1-bit Adam compressed exchange ON the wire (VERDICT r2 next #4, r3 #6/#9).

Four planes, all on the virtual 8-device mesh:
  * volume accounting — metrics["comm_bytes"] must drop ~30x when the
    compression stage starts (dense fp32 ring-allreduce vs BIT-PACKED
    uint8 all_to_all + all_gather, 8 signs/byte; the int8 fallback
    keeps the historical ~4x);
  * HLO — the compiled step must CONTAIN u8 (packed) / s8 (fallback)
    all-to-all/all-gather collectives;
  * convergence — training through the freeze boundary keeps improving,
    and tracks the dynamics-only (GSPMD) OneBitAdam path;
  * ZeRO stage 1 — sharded v + fp32 master with bf16 param re-gather
    (the reference supports 1-bit Adam with ZeRO <= 1).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM

WORLD = 8
FREEZE = 3


def _config(freeze_step=FREEZE, backend="compressed", stage=0, packing=None):
    params = {"lr": 1e-3, "freeze_step": freeze_step}
    if backend:
        params["comm_backend_name"] = backend
    if packing:
        params["onebit_packing"] = packing
    return {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "zero_optimization": {"stage": stage},
        "optimizer": {"type": "OneBitAdam", "params": params},
        "bf16": {"enabled": True},
        "steps_per_print": 10 ** 9,
    }


def _model():
    return TransformerLM(TransformerConfig(
        vocab_size=128, n_embd=32, n_layer=2, n_head=4, max_seq_len=32))


def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(0, 128, (16, 32)).astype(np.int32)}
            for _ in range(n)]


@pytest.mark.parametrize("packing,lo,hi", [
    ("1bit", 20.0, 34.0),   # ~8N vs ~N/4: true bit-packed wire
    ("int8", 3.0, 5.0),     # fallback: one sign per byte
])
def test_comm_bytes_drop_at_freeze_boundary(packing, lo, hi):
    engine, _, _, _ = ds.initialize(model=_model(),
                                    config=_config(packing=packing))
    dense, compressed = [], []
    for i, b in enumerate(_batches(6)):
        engine.train_batch(batch=b)
        vol = float(engine._last_metrics["comm_bytes"])
        (dense if i < FREEZE else compressed).append(vol)
    assert all(v == dense[0] for v in dense)
    assert all(v == compressed[0] for v in compressed)
    ratio = dense[0] / compressed[0]
    assert lo < ratio < hi, (packing, ratio)


@pytest.mark.parametrize("packing,dtype_tag", [("1bit", "u8"),
                                               ("int8", "s8")])
def test_compiled_step_contains_packed_collectives(packing, dtype_tag):
    engine, _, _, _ = ds.initialize(model=_model(),
                                    config=_config(packing=packing))
    b = _batches(1)[0]
    stacked = engine._stack_micro_batches(b)
    if engine.state is None:
        first = jax.tree_util.tree_map(lambda x: x[0], stacked)
        engine._build_state(engine._init_params_from_batch(first))
    hlo = engine._jit_train_batch.lower(engine.state, stacked) \
        .compile().as_text()
    # the compressed exchange must be present as narrow collectives — this
    # fails if gradient exchange silently reverts to dense fp32 only
    assert "all-to-all" in hlo, "all_to_all collective missing from HLO"
    packed_collective = any(
        ("all-to-all" in line or "all-gather" in line) and dtype_tag in line
        for line in hlo.splitlines())
    assert packed_collective, \
        f"no {dtype_tag} collective in the compiled step"


def test_convergence_through_freeze_boundary():
    batches = _batches(24, seed=1)

    def run(backend):
        engine, _, _, _ = ds.initialize(
            model=_model(), config=_config(freeze_step=6, backend=backend))
        return [float(engine.train_batch(batch=b)) for b in batches]

    wired = run("compressed")
    plain = run(None)  # dynamics-only GSPMD path
    # both decrease end-to-end and the wired path tracks the dynamics-only
    # path (identical warmup; compression differs only by the two-stage
    # error-feedback quantization)
    assert wired[-1] < wired[0]
    assert plain[-1] < plain[0]
    assert abs(wired[-1] - plain[-1]) < 0.35, (wired[-1], plain[-1])


def test_state_has_per_rank_error_buffers():
    engine, _, _, _ = ds.initialize(model=_model(), config=_config())
    engine.train_batch(batch=_batches(1)[0])
    ob = engine.state["onebit"]
    n_pad = ob["m"].shape[0]
    assert ob["we"].shape == (WORLD, n_pad)
    assert ob["se"].shape == (WORLD, n_pad // WORLD)
    # error buffers are sharded one row per rank over the data axis
    assert ob["we"].sharding.spec == jax.sharding.PartitionSpec("data")


def test_rejected_configs():
    with pytest.raises(ValueError, match="ZeRO stage"):
        ds.initialize(model=_model(), config=_config(stage=2))
    with pytest.raises(ValueError, match="onebit_packing"):
        ds.initialize(model=_model(), config=_config(packing="2bit"))


def test_zero_stage1_sharded_state_and_convergence():
    """Stage 1: v + fp32 master shard over the data axis (one row per
    rank), params re-gather in bf16, and the trajectory still tracks the
    stage-0 wire path through the freeze boundary."""
    batches = _batches(12, seed=3)

    def run(stage):
        engine, _, _, _ = ds.initialize(
            model=_model(), config=_config(freeze_step=4, stage=stage))
        losses = [float(engine.train_batch(batch=b)) for b in batches]
        return losses, engine

    l1, eng1 = run(1)
    l0, _ = run(0)
    assert l1[-1] < l1[0]
    assert abs(l1[-1] - l0[-1]) < 0.35, (l1[-1], l0[-1])

    ob = eng1.state["onebit"]
    n_pad = ob["m"].shape[0]
    assert ob["v"].shape == (WORLD, n_pad // WORLD)
    assert ob["master_flat"].shape == (WORLD, n_pad // WORLD)
    assert ob["master_flat"].sharding.spec == \
        jax.sharding.PartitionSpec("data")
    assert eng1.state["master"] is None  # no replicated fp32 master

    # stage-1 wire includes the bf16 param gather on top of the packed
    # momentum exchange
    vol1 = float(eng1._last_metrics["comm_bytes"])
    n = n_pad
    assert vol1 > 2 * n  # param gather dominates


def test_onebit_checkpoint_roundtrip(tmp_path):
    """Momentum + error buffers (and the stage-1 sharded master) survive
    save/load — a resume must not silently re-zero the exchange."""
    engine, _, _, _ = ds.initialize(model=_model(),
                                    config=_config(stage=1))
    batches = _batches(FREEZE + 2, seed=5)
    for b in batches:
        engine.train_batch(batch=b)
    engine.save_checkpoint(str(tmp_path))
    m_before = np.asarray(engine.state["onebit"]["m"])
    l_next = float(engine.train_batch(batch=batches[0]))

    eng2, _, _, _ = ds.initialize(model=_model(), config=_config(stage=1))
    eng2.train_batch(batch=batches[0])  # build state
    eng2.load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(np.asarray(eng2.state["onebit"]["m"]),
                               m_before, rtol=1e-6)
    l_next2 = float(eng2.train_batch(batch=batches[0]))
    assert abs(l_next - l_next2) < 5e-3, (l_next, l_next2)

    # PARTIAL restore (no optimizer states): the stage-1 sharded master
    # must be re-seeded from the loaded weights — a stale init-time
    # master would silently reset the model on the next step
    eng3, _, _, _ = ds.initialize(model=_model(), config=_config(stage=1))
    eng3.train_batch(batch=batches[0])  # build state
    eng3.load_checkpoint(str(tmp_path), load_optimizer_states=False)
    # the step loss is computed on the PRE-update params, so a correct
    # restore reproduces the full-restore engine's loss exactly (a stale
    # master would instead regenerate near-init params)
    l3 = float(eng3.train_batch(batch=batches[0]))
    assert abs(l3 - l_next) < 5e-3, (l3, l_next)


@pytest.mark.parametrize("stage", [0, 1])
def test_wire_composes_with_tensor_parallelism(stage):
    """dp=4 x tp=2: the exchange is manual over `data` only, the model
    axis stays GSPMD-auto (reference: OneBitAdam under Megatron TP,
    fp16/onebit/adam.py:13). The dp4xtp2 trajectory must track the
    dp8 wire trajectory, TP params must STAY TP-sharded after steps,
    and the packed collectives must still be in the HLO."""
    from deepspeed_tpu.parallel import initialize_mesh
    from deepspeed_tpu.parallel import mesh as mesh_mod
    from deepspeed_tpu.models.transformer_lm import transformer_sharding_rules
    from deepspeed_tpu.runtime.fp16.onebit import wire
    from deepspeed_tpu.runtime.zero.policy import ShardingRules

    if not wire._supports_auto_axes():
        pytest.skip("shard_map axis_names (jax >= 0.9) required for tp>1")

    batches = _batches(10, seed=7)

    def run(mesh, rules=None):
        engine, _, _, _ = ds.initialize(
            model=_model(), config=_config(freeze_step=4, stage=stage),
            sharding_rules=rules, mesh=mesh)
        losses = [float(engine.train_batch(batch=b)) for b in batches]
        return losses, engine

    mesh_mod.reset_mesh()
    l_tp, eng_tp = run(initialize_mesh(data=4, model=2),
                       ShardingRules(transformer_sharding_rules()))
    mesh_mod.reset_mesh()
    l_dp, _ = run(initialize_mesh(data=8))
    mesh_mod.reset_mesh()

    assert l_tp[-1] < l_tp[0]
    # the wire's momentum is global (flat over the whole model), so the
    # dp4xtp2 exchange compresses the same vector as dp8 with half the
    # ranks — trajectories track, they are not bitwise equal
    assert abs(l_tp[-1] - l_dp[-1]) < 0.35, (l_tp[-1], l_dp[-1])

    # TP layout survives the step: a TP-sharded kernel is still sharded
    # over the model axis (the constraint in wire.build_train_step)
    flat = jax.tree_util.tree_leaves_with_path(eng_tp.state["params"])
    tp_leaves = [leaf for path, leaf in flat
                 if "up_proj" in "/".join(str(p) for p in path)
                 and leaf.ndim >= 2]
    assert tp_leaves, "no TP kernels found"
    for leaf in tp_leaves:
        assert any(ax == "model" for ax in leaf.sharding.spec
                   if ax is not None), \
            f"TP kernel lost its model-axis sharding: {leaf.sharding.spec}"


def test_compression_stage_actually_compresses():
    """After freeze, worker error becomes non-zero (compression residual)."""
    engine, _, _, _ = ds.initialize(model=_model(), config=_config())
    for b in _batches(FREEZE + 2):
        engine.train_batch(batch=b)
    we = np.asarray(engine.state["onebit"]["we"])
    assert np.abs(we).max() > 0, "worker error never updated — no compression"
