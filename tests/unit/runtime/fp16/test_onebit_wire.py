"""1-bit Adam compressed exchange ON the wire (VERDICT r2 next #4).

Three planes, all on the virtual 8-device mesh:
  * volume accounting — metrics["comm_bytes"] must drop ~4x when the
    compression stage starts (dense fp32 ring-allreduce vs int8
    all_to_all + all_gather);
  * HLO — the compiled step must CONTAIN s8 all-to-all/all-gather
    collectives (fails if the compressed collective is bypassed);
  * convergence — training through the freeze boundary keeps improving,
    and tracks the dynamics-only (GSPMD) OneBitAdam path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM

WORLD = 8
FREEZE = 3


def _config(freeze_step=FREEZE, backend="compressed", stage=0):
    return {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "zero_optimization": {"stage": stage},
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": 1e-3, "freeze_step": freeze_step,
                                 **({"comm_backend_name": backend}
                                    if backend else {})}},
        "bf16": {"enabled": True},
        "steps_per_print": 10 ** 9,
    }


def _model():
    return TransformerLM(TransformerConfig(
        vocab_size=128, n_embd=32, n_layer=2, n_head=4, max_seq_len=32))


def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(0, 128, (16, 32)).astype(np.int32)}
            for _ in range(n)]


def test_comm_bytes_drop_at_freeze_boundary():
    engine, _, _, _ = ds.initialize(model=_model(), config=_config())
    dense, compressed = [], []
    for i, b in enumerate(_batches(6)):
        engine.train_batch(batch=b)
        vol = float(engine._last_metrics["comm_bytes"])
        (dense if i < FREEZE else compressed).append(vol)
    assert all(v == dense[0] for v in dense)
    assert all(v == compressed[0] for v in compressed)
    ratio = dense[0] / compressed[0]
    # dense ring allreduce ~8N vs int8 a2a+ag ~2N → ~4x (scales shave a hair)
    assert 3.0 < ratio < 5.0, ratio


def test_compiled_step_contains_int8_collectives():
    engine, _, _, _ = ds.initialize(model=_model(), config=_config())
    b = _batches(1)[0]
    stacked = engine._stack_micro_batches(b)
    if engine.state is None:
        first = jax.tree_util.tree_map(lambda x: x[0], stacked)
        engine._build_state(engine._init_params_from_batch(first))
    hlo = engine._jit_train_batch.lower(engine.state, stacked) \
        .compile().as_text()
    # the compressed exchange must be present as int8 collectives — this
    # fails if gradient exchange silently reverts to dense fp32 only
    assert "all-to-all" in hlo, "all_to_all collective missing from HLO"
    s8_collective = any(
        ("all-to-all" in line or "all-gather" in line) and "s8" in line
        for line in hlo.splitlines())
    assert s8_collective, "no int8 collective in the compiled step"


def test_convergence_through_freeze_boundary():
    batches = _batches(24, seed=1)

    def run(backend):
        engine, _, _, _ = ds.initialize(
            model=_model(), config=_config(freeze_step=6, backend=backend))
        return [float(engine.train_batch(batch=b)) for b in batches]

    wired = run("compressed")
    plain = run(None)  # dynamics-only GSPMD path
    # both decrease end-to-end and the wired path tracks the dynamics-only
    # path (identical warmup; compression differs only by the two-stage
    # error-feedback quantization)
    assert wired[-1] < wired[0]
    assert plain[-1] < plain[0]
    assert abs(wired[-1] - plain[-1]) < 0.35, (wired[-1], plain[-1])


def test_state_has_per_rank_error_buffers():
    engine, _, _, _ = ds.initialize(model=_model(), config=_config())
    engine.train_batch(batch=_batches(1)[0])
    ob = engine.state["onebit"]
    n_pad = ob["m"].shape[0]
    assert ob["we"].shape == (WORLD, n_pad)
    assert ob["se"].shape == (WORLD, n_pad // WORLD)
    # error buffers are sharded one row per rank over the data axis
    assert ob["we"].sharding.spec == jax.sharding.PartitionSpec("data")


def test_rejected_configs():
    with pytest.raises(ValueError, match="ZeRO stage"):
        ds.initialize(model=_model(), config=_config(stage=1))


def test_compression_stage_actually_compresses():
    """After freeze, worker error becomes non-zero (compression residual)."""
    engine, _, _, _ = ds.initialize(model=_model(), config=_config())
    for b in _batches(FREEZE + 2):
        engine.train_batch(batch=b)
    we = np.asarray(engine.state["onebit"]["we"])
    assert np.abs(we).max() > 0, "worker error never updated — no compression"
