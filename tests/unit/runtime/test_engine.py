"""Engine API tests: forward/backward/step parity, train_batch, fp16 loss
scaling, checkpoint save/load (analog of reference
tests/unit/runtime/test_ds_initialize.py + half_precision + checkpoint)."""

import numpy as np
import pytest

import deepspeed_tpu as ds
from tests.unit.simple_model import (
    SimpleModel,
    base_config,
    random_batch,
    tiny_gpt2,
    token_batch,
)


def _make_engine(stage=0, dtype="fp32", micro=2, gas=1, extra=None):
    model = SimpleModel(hidden_dim=16)
    cfg = base_config(stage=stage, dtype=dtype, micro=micro, gas=gas, extra=extra)
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    return engine


def test_initialize_returns_tuple():
    model = SimpleModel()
    out = ds.initialize(model=model, config=base_config())
    assert len(out) == 4


def test_train_batch_loss_decreases():
    engine = _make_engine()
    batch = random_batch(16)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(10)]
    assert losses[-1] < losses[0]


def test_forward_backward_step_matches_train_batch():
    """The eager triple must produce the same params as the fused path."""
    import jax

    e1 = _make_engine()
    e2 = _make_engine()
    batch = random_batch(16, seed=3)
    e1.train_batch(batch=batch)

    loss = e2.forward(batch)
    e2.backward(loss)
    e2.step()

    # same per-micro rng derivation isn't guaranteed between paths unless
    # gas=1 and the micro index is 0 — which holds here
    p1 = jax.tree_util.tree_leaves(e1.state["params"])
    p2 = jax.tree_util.tree_leaves(e2.state["params"])
    for a, b in zip(p1, p2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_gradient_accumulation_boundary():
    engine = _make_engine(gas=2)
    batch = random_batch(16, seed=1)
    assert engine.is_gradient_accumulation_boundary() is False
    loss = engine.forward(batch)
    engine.backward(loss)
    engine.step()  # not a boundary: no-op
    assert engine.global_steps == 0
    loss = engine.forward(batch)
    engine.backward(loss)
    engine.step()
    assert engine.global_steps == 1


def test_fp16_dynamic_loss_scale_runs():
    engine = _make_engine(dtype="fp16", extra={
        "fp16": {"enabled": True, "initial_scale_power": 8, "loss_scale_window": 2}})
    batch = random_batch(16)
    for _ in range(4):
        loss = engine.train_batch(batch=batch)
    assert np.isfinite(float(loss))
    assert engine.state["scale"] is not None
    assert float(engine.state["scale"].loss_scale) >= 2 ** 8


def test_fp16_overflow_skips_step():
    """Force an inf gradient via a huge loss-scale and check params hold."""
    import jax

    engine = _make_engine(dtype="fp16", extra={
        "fp16": {"enabled": True, "initial_scale_power": 40, "hysteresis": 1}})
    batch = random_batch(16)
    engine.forward(batch)  # builds lazy state without updating params
    engine._pending = None
    before = jax.device_get(engine.state)
    engine.train_batch(batch=batch)
    after = jax.device_get(engine.state)
    # fp32 master unchanged (step skipped), scale halved
    b = jax.tree_util.tree_leaves(before["master"])
    a = jax.tree_util.tree_leaves(after["master"])
    for x, y in zip(b, a):
        np.testing.assert_array_equal(x, y)
    assert float(after["scale"].loss_scale) < float(before["scale"].loss_scale)


def test_lr_schedule_in_step():
    model = SimpleModel()
    cfg = base_config()
    cfg["scheduler"] = {"type": "WarmupLR",
                        "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01,
                                   "warmup_num_steps": 10, "warmup_type": "linear"}}
    engine, _, _, sched = ds.initialize(model=model, config=cfg)
    batch = random_batch(16)
    engine.train_batch(batch=batch)
    lr1 = engine.get_lr()[0]
    for _ in range(5):
        engine.train_batch(batch=batch)
    lr2 = engine.get_lr()[0]
    assert lr2 > lr1


@pytest.mark.parametrize("stage", [0, 1, 3])
def test_checkpoint_save_load_roundtrip(tmp_path, stage):
    import jax

    engine = _make_engine(stage=stage, dtype="bf16")
    batch = random_batch(16)
    for _ in range(3):
        engine.train_batch(batch=batch)
    engine.save_checkpoint(str(tmp_path), tag="ck")
    ref = jax.device_get(engine.state)

    engine2 = _make_engine(stage=stage, dtype="bf16")
    engine2.train_batch(batch=random_batch(16, seed=9))  # diverge
    engine2.load_checkpoint(str(tmp_path), tag="ck")
    got = jax.device_get(engine2.state)
    for a, b in zip(jax.tree_util.tree_leaves(ref["params"]),
                    jax.tree_util.tree_leaves(got["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert engine2.global_steps == 3
    # training continues identically
    l1 = float(engine.train_batch(batch=batch))
    l2 = float(engine2.train_batch(batch=batch))
    assert abs(l1 - l2) < 1e-5


def test_checkpoint_latest_tag(tmp_path):
    engine = _make_engine()
    engine.train_batch(batch=random_batch(16))
    engine.save_checkpoint(str(tmp_path))
    assert (tmp_path / "latest").read_text() == "global_step1"
    engine.load_checkpoint(str(tmp_path))  # resolves via latest


def test_gpt2_train_and_eval():
    model = tiny_gpt2()
    cfg = base_config(micro=2, gas=1)
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    batch = token_batch(16, seq=16)
    l0 = float(engine.train_batch(batch=batch))
    for _ in range(5):
        loss = engine.train_batch(batch=batch)
    assert float(loss) < l0


def test_dataloader_path():
    from tests.unit.simple_model import random_dataset

    model = SimpleModel()
    data = random_dataset(256)
    engine, _, loader, _ = ds.initialize(model=model, config=base_config(),
                                         training_data=data)
    assert loader is not None
    it = iter(loader)
    loss = engine.train_batch(data_iter=it)
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# round 2: eager-path convergence parity vs the fused path (VERDICT weak #9)
# ---------------------------------------------------------------------------
def _eager_steps(engine, batches):
    """Drive forward/backward/step over the same micro order train_batch
    uses (contiguous reshape: micro i = rows [i*m:(i+1)*m])."""
    losses = []
    gas = engine.gradient_accumulation_steps()
    for batch in batches:
        micro_rows = batch["x"].shape[0] // gas
        acc = 0.0
        for i in range(gas):
            micro = {k: v[i * micro_rows:(i + 1) * micro_rows]
                     for k, v in batch.items()}
            loss = engine.forward(micro)
            engine.backward(loss)
            acc += float(loss)
            engine.step()
        losses.append(acc / gas)
    return losses


@pytest.mark.parametrize("stage", [0, 2, 3])
def test_eager_matches_fused_trajectory(stage):
    """Multi-step, gas=2, per-ZeRO-stage: the eager triple must follow the
    fused train_batch trajectory (params AND losses)."""
    import jax

    e1 = _make_engine(stage=stage, micro=2, gas=2)
    e2 = _make_engine(stage=stage, micro=2, gas=2)
    batches = [random_batch(e1.train_batch_size(), seed=50 + i)
               for i in range(3)]
    fused = [float(e1.train_batch(batch=b)) for b in batches]
    eager = _eager_steps(e2, batches)
    np.testing.assert_allclose(eager, fused, rtol=1e-4, atol=1e-5)
    assert e1.global_steps == e2.global_steps == 3
    for a, b in zip(jax.tree_util.tree_leaves(e1.state["params"]),
                    jax.tree_util.tree_leaves(e2.state["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_eager_matches_fused_fp16_loss_scaling():
    """The dynamic loss-scale state must evolve identically on both paths
    (scale halving on overflow, growth on the window)."""
    import jax

    extra = {"fp16": {"enabled": True, "initial_scale_power": 10,
                      "loss_scale_window": 2}}
    e1 = _make_engine(dtype="fp16", micro=2, gas=2, extra=extra)
    e2 = _make_engine(dtype="fp16", micro=2, gas=2, extra=extra)
    batches = [random_batch(e1.train_batch_size(), seed=80 + i)
               for i in range(4)]
    fused = [float(e1.train_batch(batch=b)) for b in batches]
    eager = _eager_steps(e2, batches)
    np.testing.assert_allclose(eager, fused, rtol=2e-3, atol=2e-3)
    s1 = float(np.asarray(e1.state["scale"].loss_scale))
    s2 = float(np.asarray(e2.state["scale"].loss_scale))
    assert s1 == s2, (s1, s2)
