"""ZeRO-Infinity TRAINING-time parameter offload (the param tier).

Reference capability matched: ``zero_optimization.offload_param.device:
"cpu"|"nvme"`` trains models whose parameters exceed device memory
(``partition_parameters.py:616`` remote_device +
``swap_tensor/partitioned_param_swapper.py`` + stage3 prefetch/release).
Here the TPU-native path streams the scan-stacked block through the chip
per layer (runtime/zero/param_offload.py); these tests pin its TRAJECTORY
to the resident optimizer-offload engine — same CPU-Adam numerics, same
grads up to reduction order — on the virtual 8-device CPU mesh, so the
data-parallel per-layer grad reduction is exercised too.
"""

import os
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import (
    TransformerLM,
    transformer_config,
)
from deepspeed_tpu.parallel import reset_mesh

_MODEL = dict(vocab_size=128, n_embd=32, n_layer=3, n_head=4,
              max_seq_len=32, dtype=jnp.float32)


def _run(zero, steps=4, family="gpt2", gas=2, model_kw=None, conf_extra=None):
    reset_mesh()
    cfg = transformer_config(family, **{**_MODEL, **(model_kw or {})})
    conf = {"train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": gas,
            "zero_optimization": zero,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 1e-3, "weight_decay": 0.01}},
            "gradient_clipping": 1.0, "steps_per_print": 10 ** 9}
    conf.update(conf_extra or {})
    engine, _, _, _ = ds.initialize(model=TransformerLM(cfg), config=conf)
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(steps):
        batch = {"input_ids": rng.integers(
            0, 128, (engine.train_batch_size(), 32)).astype(np.int32)}
        losses.append(float(engine.train_batch(batch=batch)))
    return losses, engine


def test_param_offload_cpu_matches_resident_offload():
    """Streamed-params training tracks the resident engine with the same
    host Adam, across gas accumulation + global-norm clipping, under dp=8
    (per-layer grad reduction via GSPMD)."""
    base, _ = _run({"stage": 0, "offload_optimizer": {"device": "cpu"}})
    po, eng = _run({"stage": 0, "offload_param": {"device": "cpu"}})
    np.testing.assert_allclose(po, base, rtol=2e-4, atol=2e-4)
    assert eng._param_offload is not None
    t = eng._param_offload.last_timings
    assert t["forward_stream_s"] > 0 and t["backward_stream_s"] > 0


def test_param_offload_untied_head_family():
    """llama preset: untied lm_head grads flow through the resident tier."""
    base, _ = _run({"stage": 0, "offload_optimizer": {"device": "cpu"}},
                   family="llama", steps=3)
    po, _ = _run({"stage": 0, "offload_param": {"device": "cpu"}},
                 family="llama", steps=3)
    np.testing.assert_allclose(po, base, rtol=2e-4, atol=2e-4)


def test_param_offload_nvme_store(tmp_path):
    """device=nvme: per-layer packed files via the AIO tier, host stacked
    store released, trajectory unchanged."""
    base, _ = _run({"stage": 0, "offload_optimizer": {"device": "cpu"}},
                   steps=3)
    po, eng = _run({"stage": 0, "offload_param": {
        "device": "nvme", "nvme_path": str(tmp_path)}}, steps=3)
    np.testing.assert_allclose(po, base, rtol=2e-4, atol=2e-4)
    files = [f for f in os.listdir(tmp_path) if f.startswith("layer_")]
    assert len(files) == 3
    assert eng._param_offload.store.stacked is None  # host copy released


def test_param_offload_with_nvme_optimizer_moments_only(tmp_path):
    """Composition with offload_optimizer device=nvme swap_master=False:
    moments swap to disk, fp32 master stays DRAM-resident (the split that
    fits a 125 GB host for 10B-class models)."""
    base, _ = _run({"stage": 0, "offload_optimizer": {"device": "cpu"}},
                   steps=3)
    po, eng = _run({"stage": 0,
                    "offload_param": {"device": "cpu"},
                    "offload_optimizer": {
                        "device": "nvme", "nvme_path": str(tmp_path),
                        "swap_master": False}}, steps=3)
    np.testing.assert_allclose(po, base, rtol=2e-4, atol=2e-4)
    opt = eng._param_offload.opt
    assert opt.nvme and not opt.swap_master
    files = os.listdir(tmp_path)
    assert any(f.endswith(".m.bin") for f in files)
    assert not any(f.endswith(".master.bin") for f in files)
    # master resident between steps; moments swapped out
    assert all(a is not None for a in opt.master.values())
    assert all(a is None for p, a in opt.m.items() if opt._float[p])


def test_param_offload_checkpoint_roundtrip(tmp_path):
    po, eng = _run({"stage": 0, "offload_param": {"device": "cpu"}}, steps=3)
    ck = os.path.join(str(tmp_path), "ck")
    eng.save_checkpoint(ck)
    probe = {"input_ids": np.random.default_rng(5).integers(
        0, 128, (eng.train_batch_size(), 32)).astype(np.int32)}
    ev1 = eng._param_offload.eval_loss(probe)
    l1 = float(eng.train_batch(batch=probe))

    _, eng2 = _run({"stage": 0, "offload_param": {"device": "cpu"}}, steps=1)
    eng2.load_checkpoint(ck)
    ev2 = eng2._param_offload.eval_loss(probe)
    assert abs(ev1 - ev2) < 1e-5
    l2 = float(eng2.train_batch(batch=probe))
    assert abs(l1 - l2) < 1e-4  # optimizer momentum restored too


def test_param_offload_bf16_memorizes():
    """bf16 compute path: one fixed batch, loss must fall monotonically."""
    reset_mesh()
    cfg = transformer_config("gpt2", **{**_MODEL, "dtype": jnp.bfloat16})
    engine, _, _, _ = ds.initialize(
        model=TransformerLM(cfg),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "zero_optimization": {"offload_param": {"device": "cpu"}},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True}, "steps_per_print": 10 ** 9})
    batch = {"input_ids": np.random.default_rng(3).integers(
        0, 128, (engine.train_batch_size(), 32)).astype(np.int32)}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(4)]
    assert losses[-1] < losses[0], losses


def test_param_offload_rejects_unsupported():
    reset_mesh()
    cfg = transformer_config("gpt2", **_MODEL)
    zero = {"offload_param": {"device": "cpu"}}

    with pytest.raises(ValueError, match="fp16|bf16"):
        ds.initialize(model=TransformerLM(cfg), config={
            "train_micro_batch_size_per_gpu": 1,
            "zero_optimization": zero, "fp16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})

    with pytest.raises(ValueError, match="Adam"):
        ds.initialize(model=TransformerLM(cfg), config={
            "train_micro_batch_size_per_gpu": 1,
            "zero_optimization": zero,
            "optimizer": {"type": "SGD", "params": {"lr": 1e-3}}})

    # round 5: dropout>0 and GPT2LMHeadModel are SUPPORTED (rng threading +
    # adapter registry) — covered by the trajectory/determinism tests; a
    # module with no streamable trunk still fails with the family list
    from deepspeed_tpu.models.bert import BertConfig, BertModel

    with pytest.raises(ValueError, match="TransformerLM and GPT2LMHeadModel"):
        ds.initialize(
            model=BertModel(BertConfig(
                vocab_size=64, max_position_embeddings=32, hidden_size=32,
                num_hidden_layers=2, num_attention_heads=4,
                intermediate_size=64)),
            config={"train_micro_batch_size_per_gpu": 1,
                    "zero_optimization": zero,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})


def test_param_offload_eager_api_raises():
    reset_mesh()
    cfg = transformer_config("gpt2", **_MODEL)
    engine, _, _, _ = ds.initialize(
        model=TransformerLM(cfg),
        config={"train_micro_batch_size_per_gpu": 1,
                "zero_optimization": {"offload_param": {"device": "cpu"}},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    with pytest.raises(RuntimeError, match="train_batch"):
        engine.forward({"input_ids": np.zeros((8, 32), np.int32)})


def _run_gpt2(zero, steps=4, gas=2, dropout=0.0, seed=1234):
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    reset_mesh()
    cfg = GPT2Config(vocab_size=128, n_positions=32, n_embd=32, n_layer=3,
                     n_head=4, dtype=jnp.float32, dropout=dropout)
    conf = {"train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": gas,
            "zero_optimization": zero,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 1e-3, "weight_decay": 0.01}},
            "gradient_clipping": 1.0, "steps_per_print": 10 ** 9,
            "seed": seed}
    engine, _, _, _ = ds.initialize(model=GPT2LMHeadModel(cfg), config=conf)
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(steps):
        batch = {"input_ids": rng.integers(
            0, 128, (engine.train_batch_size(), 32)).astype(np.int32)}
        losses.append(float(engine.train_batch(batch=batch)))
    return losses, engine


def test_param_offload_gpt2_matches_resident_offload():
    """Round-5 generalization (VERDICT r4 next-#3): GPT2LMHeadModel streams
    through the same runner via the adapter registry, trajectory pinned to
    the resident optimizer-offload engine."""
    base, _ = _run_gpt2({"stage": 0, "offload_optimizer": {"device": "cpu"}})
    po, eng = _run_gpt2({"stage": 0, "offload_param": {"device": "cpu"}})
    np.testing.assert_allclose(po, base, rtol=2e-4, atol=2e-4)
    assert eng._param_offload is not None


def test_param_offload_dropout_trains_deterministically():
    """dropout>0 (round-5 rng threading): two identically-seeded runs are
    bit-identical; the loss decreases on a fixed data stream; a different
    seed gives a different (but converging) trajectory."""
    a, _ = _run_gpt2({"stage": 0, "offload_param": {"device": "cpu"}},
                     dropout=0.2, steps=4)
    b, _ = _run_gpt2({"stage": 0, "offload_param": {"device": "cpu"}},
                     dropout=0.2, steps=4)
    assert a == b, "same seed must reproduce the dropout trajectory"
    c, _ = _run_gpt2({"stage": 0, "offload_param": {"device": "cpu"}},
                     dropout=0.2, steps=4, seed=99)
    assert c != a, "different seed must change the dropout masks"
    assert a[-1] < a[0], "loss must decrease under dropout"


def test_param_offload_dropout_transformer_lm():
    """TransformerLM with dropout>0 under param offload trains and is
    seed-deterministic (the round-4 dropout=0 restriction is lifted for
    both adapter families)."""
    a, _ = _run({"stage": 0, "offload_param": {"device": "cpu"}},
                model_kw={"dropout": 0.2}, steps=3,
                conf_extra={"seed": 7})
    b, _ = _run({"stage": 0, "offload_param": {"device": "cpu"}},
                model_kw={"dropout": 0.2}, steps=3,
                conf_extra={"seed": 7})
    assert a == b
    assert a[-1] < a[0]


def test_param_offload_nvme_bounded_finalize(tmp_path):
    """VERDICT r4 next-#4: the layer-streamed finalize must not
    materialize the full new param tree — transient host allocations during
    step() stay O(layer) as depth grows. Measured with tracemalloc around
    one global step: the finalize-phase peak delta for a 2x-deeper model
    stays well under 2x (O(model) materialization would double it)."""
    import tracemalloc

    def peak_for(n_layer):
        reset_mesh()
        cfg = transformer_config(
            "gpt2", **{**_MODEL, "n_layer": n_layer, "n_embd": 64})
        engine, _, _, _ = ds.initialize(
            model=TransformerLM(cfg),
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 1,
                    "zero_optimization": {
                        "offload_param": {"device": "nvme",
                                          "nvme_path": str(tmp_path / str(n_layer))},
                        "offload_optimizer": {"device": "nvme",
                                              "nvme_path": str(tmp_path / f"opt{n_layer}")},
                    },
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "steps_per_print": 10 ** 9})
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(
            0, 128, (engine.train_batch_size(), 32)).astype(np.int32)}
        engine.train_batch(batch=batch)  # warmup: compiles + first swap
        tracemalloc.start()
        tracemalloc.reset_peak()
        engine.train_batch(batch=batch)
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        return peak

    p4, p8 = peak_for(4), peak_for(8)
    # grads accumulate per-row and free per-layer; the update itself is
    # O(row). Allow slack for allocator noise but reject O(model) scaling.
    assert p8 < 1.7 * max(p4, 1), (p4, p8)


def _run_moe(zero, steps=4, gas=2, dropout=0.0, use_rts=False, k=1,
             fixed_batch=False):
    # k=1 + use_rts=False for trajectory parity: top-2 gating adds gumbel
    # noise to the second-expert pick whenever a gating rng is present,
    # and RTS draws it too — those rng STREAMS necessarily differ between
    # the resident engine (one flax rng folded per module path) and the
    # per-layer streamed apply, so bit-parity only exists on the
    # rng-independent gating path
    from deepspeed_tpu.models.gpt_moe import GPTMoEConfig, GPTMoEModel

    reset_mesh()
    cfg = GPTMoEConfig(vocab_size=128, n_positions=32, n_embd=32, n_layer=4,
                       n_head=4, moe_every=2, num_experts=4, k=k,
                       dtype=jnp.float32, dropout=dropout, use_rts=use_rts)
    conf = {"train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": gas,
            "zero_optimization": zero,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 1e-3, "weight_decay": 0.01}},
            "gradient_clipping": 1.0, "steps_per_print": 10 ** 9}
    engine, _, _, _ = ds.initialize(model=GPTMoEModel(cfg), config=conf)
    rng = np.random.default_rng(0)
    losses = []
    fixed = {"input_ids": rng.integers(
        0, 128, (engine.train_batch_size(), 32)).astype(np.int32)}
    for _ in range(steps):
        batch = fixed if fixed_batch else {"input_ids": rng.integers(
            0, 128, (engine.train_batch_size(), 32)).astype(np.int32)}
        losses.append(float(engine.train_batch(batch=batch)))
    return losses, engine


def test_param_offload_gpt_moe_matches_resident_offload():
    """Heterogeneous trunk (round 5): alternating dense/MoE blocks stream
    as per-layer subtrees (HeteroLayerStore + per-layer-key optimizer
    updates); trajectory — including the aux-loss term and its router
    gradients — pinned to the resident optimizer-offload engine."""
    base, _ = _run_moe({"stage": 0, "offload_optimizer": {"device": "cpu"}})
    po, eng = _run_moe({"stage": 0, "offload_param": {"device": "cpu"}})
    np.testing.assert_allclose(po, base, rtol=3e-4, atol=3e-4)
    assert eng._param_offload is not None
    assert eng._param_offload.hetero
    # two structural kinds compiled: dense and 4-expert MoE
    assert len(eng._param_offload.store.wires) == 2


def test_param_offload_gpt_moe_rts_trains():
    """k=2 + use_rts=True (the reference's NLG recipe): gumbel
    second-expert noise and random-token-selection draw the gating rng
    under streaming — a fixed batch must memorize; no bit-parity claim vs
    the resident engine (different rng streams, documented in the
    adapter)."""
    po, _ = _run_moe({"stage": 0, "offload_param": {"device": "cpu"}},
                     steps=5, use_rts=True, k=2, fixed_batch=True)
    assert all(np.isfinite(po)), po
    assert po[-1] < po[0], po


def test_param_offload_gpt_moe_nvme(tmp_path):
    """MoE layers round-trip the NVMe tier (per-kind wire formats)."""
    base, _ = _run_moe({"stage": 0, "offload_optimizer": {"device": "cpu"}},
                       steps=3)
    po, eng = _run_moe({"stage": 0, "offload_param": {
        "device": "nvme", "nvme_path": str(tmp_path)}}, steps=3)
    np.testing.assert_allclose(po, base, rtol=3e-4, atol=3e-4)
    files = [f for f in os.listdir(tmp_path) if f.startswith("layer_")]
    assert len(files) == 4
