"""ZeRO-Infinity TRAINING-time parameter offload (the param tier).

Reference capability matched: ``zero_optimization.offload_param.device:
"cpu"|"nvme"`` trains models whose parameters exceed device memory
(``partition_parameters.py:616`` remote_device +
``swap_tensor/partitioned_param_swapper.py`` + stage3 prefetch/release).
Here the TPU-native path streams the scan-stacked block through the chip
per layer (runtime/zero/param_offload.py); these tests pin its TRAJECTORY
to the resident optimizer-offload engine — same CPU-Adam numerics, same
grads up to reduction order — on the virtual 8-device CPU mesh, so the
data-parallel per-layer grad reduction is exercised too.
"""

import os
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import (
    TransformerLM,
    transformer_config,
)
from deepspeed_tpu.parallel import reset_mesh

_MODEL = dict(vocab_size=128, n_embd=32, n_layer=3, n_head=4,
              max_seq_len=32, dtype=jnp.float32)


def _run(zero, steps=4, family="gpt2", gas=2, model_kw=None, conf_extra=None):
    reset_mesh()
    cfg = transformer_config(family, **{**_MODEL, **(model_kw or {})})
    conf = {"train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": gas,
            "zero_optimization": zero,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 1e-3, "weight_decay": 0.01}},
            "gradient_clipping": 1.0, "steps_per_print": 10 ** 9}
    conf.update(conf_extra or {})
    engine, _, _, _ = ds.initialize(model=TransformerLM(cfg), config=conf)
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(steps):
        batch = {"input_ids": rng.integers(
            0, 128, (engine.train_batch_size(), 32)).astype(np.int32)}
        losses.append(float(engine.train_batch(batch=batch)))
    return losses, engine


def test_param_offload_cpu_matches_resident_offload():
    """Streamed-params training tracks the resident engine with the same
    host Adam, across gas accumulation + global-norm clipping, under dp=8
    (per-layer grad reduction via GSPMD)."""
    base, _ = _run({"stage": 0, "offload_optimizer": {"device": "cpu"}})
    po, eng = _run({"stage": 0, "offload_param": {"device": "cpu"}})
    np.testing.assert_allclose(po, base, rtol=2e-4, atol=2e-4)
    assert eng._param_offload is not None
    t = eng._param_offload.last_timings
    assert t["forward_stream_s"] > 0 and t["backward_stream_s"] > 0


def test_param_offload_untied_head_family():
    """llama preset: untied lm_head grads flow through the resident tier."""
    base, _ = _run({"stage": 0, "offload_optimizer": {"device": "cpu"}},
                   family="llama", steps=3)
    po, _ = _run({"stage": 0, "offload_param": {"device": "cpu"}},
                 family="llama", steps=3)
    np.testing.assert_allclose(po, base, rtol=2e-4, atol=2e-4)


def test_param_offload_nvme_store(tmp_path):
    """device=nvme: per-layer packed files via the AIO tier, host stacked
    store released, trajectory unchanged."""
    base, _ = _run({"stage": 0, "offload_optimizer": {"device": "cpu"}},
                   steps=3)
    po, eng = _run({"stage": 0, "offload_param": {
        "device": "nvme", "nvme_path": str(tmp_path)}}, steps=3)
    np.testing.assert_allclose(po, base, rtol=2e-4, atol=2e-4)
    files = [f for f in os.listdir(tmp_path) if f.startswith("layer_")]
    assert len(files) == 3
    assert eng._param_offload.store.stacked is None  # host copy released


def test_param_offload_with_nvme_optimizer_moments_only(tmp_path):
    """Composition with offload_optimizer device=nvme swap_master=False:
    moments swap to disk, fp32 master stays DRAM-resident (the split that
    fits a 125 GB host for 10B-class models)."""
    base, _ = _run({"stage": 0, "offload_optimizer": {"device": "cpu"}},
                   steps=3)
    po, eng = _run({"stage": 0,
                    "offload_param": {"device": "cpu"},
                    "offload_optimizer": {
                        "device": "nvme", "nvme_path": str(tmp_path),
                        "swap_master": False}}, steps=3)
    np.testing.assert_allclose(po, base, rtol=2e-4, atol=2e-4)
    opt = eng._param_offload.opt
    assert opt.nvme and not opt.swap_master
    files = os.listdir(tmp_path)
    assert any(f.endswith(".m.bin") for f in files)
    assert not any(f.endswith(".master.bin") for f in files)
    # master resident between steps; moments swapped out
    assert all(a is not None for a in opt.master.values())
    assert all(a is None for p, a in opt.m.items() if opt._float[p])


def test_param_offload_checkpoint_roundtrip(tmp_path):
    po, eng = _run({"stage": 0, "offload_param": {"device": "cpu"}}, steps=3)
    ck = os.path.join(str(tmp_path), "ck")
    eng.save_checkpoint(ck)
    probe = {"input_ids": np.random.default_rng(5).integers(
        0, 128, (eng.train_batch_size(), 32)).astype(np.int32)}
    ev1 = eng._param_offload.eval_loss(probe)
    l1 = float(eng.train_batch(batch=probe))

    _, eng2 = _run({"stage": 0, "offload_param": {"device": "cpu"}}, steps=1)
    eng2.load_checkpoint(ck)
    ev2 = eng2._param_offload.eval_loss(probe)
    assert abs(ev1 - ev2) < 1e-5
    l2 = float(eng2.train_batch(batch=probe))
    assert abs(l1 - l2) < 1e-4  # optimizer momentum restored too


def test_param_offload_bf16_memorizes():
    """bf16 compute path: one fixed batch, loss must fall monotonically."""
    reset_mesh()
    cfg = transformer_config("gpt2", **{**_MODEL, "dtype": jnp.bfloat16})
    engine, _, _, _ = ds.initialize(
        model=TransformerLM(cfg),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "zero_optimization": {"offload_param": {"device": "cpu"}},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True}, "steps_per_print": 10 ** 9})
    batch = {"input_ids": np.random.default_rng(3).integers(
        0, 128, (engine.train_batch_size(), 32)).astype(np.int32)}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(4)]
    assert losses[-1] < losses[0], losses


def test_param_offload_rejects_unsupported():
    reset_mesh()
    cfg = transformer_config("gpt2", **_MODEL)
    zero = {"offload_param": {"device": "cpu"}}

    with pytest.raises(ValueError, match="fp16|bf16"):
        ds.initialize(model=TransformerLM(cfg), config={
            "train_micro_batch_size_per_gpu": 1,
            "zero_optimization": zero, "fp16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})

    with pytest.raises(ValueError, match="Adam"):
        ds.initialize(model=TransformerLM(cfg), config={
            "train_micro_batch_size_per_gpu": 1,
            "zero_optimization": zero,
            "optimizer": {"type": "SGD", "params": {"lr": 1e-3}}})

    with pytest.raises(ValueError, match="dropout"):
        ds.initialize(
            model=TransformerLM(transformer_config(
                "gpt2", **{**_MODEL, "dropout": 0.1})),
            config={"train_micro_batch_size_per_gpu": 1,
                    "zero_optimization": zero,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})

    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    with pytest.raises(ValueError, match="TransformerLM"):
        ds.initialize(
            model=GPT2LMHeadModel(GPT2Config(
                vocab_size=64, n_positions=32, n_embd=32, n_layer=2,
                n_head=4)),
            config={"train_micro_batch_size_per_gpu": 1,
                    "zero_optimization": zero,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})


def test_param_offload_eager_api_raises():
    reset_mesh()
    cfg = transformer_config("gpt2", **_MODEL)
    engine, _, _, _ = ds.initialize(
        model=TransformerLM(cfg),
        config={"train_micro_batch_size_per_gpu": 1,
                "zero_optimization": {"offload_param": {"device": "cpu"}},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    with pytest.raises(RuntimeError, match="train_batch"):
        engine.forward({"input_ids": np.zeros((8, 32), np.int32)})
