"""MiCS hierarchical sharding + TiledLinear — analogs of reference
``tests/unit/checkpoint/test_mics_optimizer.py`` and the tiling tests in
``tests/unit/runtime/zero/test_zero_tiled.py``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.parallel import mesh as mesh_mod


class TestMiCS:
    def test_mesh_factoring(self):
        from deepspeed_tpu.runtime.zero.mics import (
            MiCS_Init,
            mics_enabled,
            mics_shard_size,
        )

        mesh = MiCS_Init(shard_size=4)
        assert mics_enabled()
        assert mics_shard_size() == 4
        dims = dict(zip(mesh.axis_names, mesh.devices.shape))
        assert dims["data"] == 4 and dims["data_outer"] == 2
        assert mesh_mod.get_data_parallel_world_size() == 8

    def test_shard_size_must_divide(self):
        from deepspeed_tpu.runtime.zero.mics import MiCS_Init

        with pytest.raises(ValueError):
            MiCS_Init(shard_size=3)

    def test_params_shard_over_group_only(self):
        """ZeRO-3 + MiCS: params sharded over the 4-chip shard group,
        replicated across the 2 replica groups."""
        from tests.unit.simple_model import SimpleModel, random_batch

        config = {
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 3, "mics_shard_size": 4,
                                  "stage3_param_persistence_threshold": 0},
            "steps_per_print": 1000,
        }
        engine, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                        config=config)
        b = random_batch(engine.train_batch_size())
        l0 = float(engine.train_batch(batch=b))
        for _ in range(4):
            l = float(engine.train_batch(batch=b))
        assert l < l0
        kernel = engine.state["params"]["linear_0"]["kernel"]
        spec = kernel.sharding.spec
        flat_axes = set()
        for entry in spec:
            if isinstance(entry, (tuple, list)):
                flat_axes.update(entry)
            elif entry is not None:
                flat_axes.add(entry)
        assert "data" in flat_axes, spec
        assert "data_outer" not in flat_axes, spec

    def test_mics_matches_plain_zero3_losses(self):
        from tests.unit.simple_model import SimpleModel, random_batch

        def run(zero_cfg):
            mesh_mod.reset_mesh()
            config = {
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": zero_cfg,
                "steps_per_print": 1000,
            }
            engine, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                            config=config)
            b = random_batch(engine.train_batch_size())
            return [float(engine.train_batch(batch=b)) for _ in range(4)]

        plain = run({"stage": 3})
        mics = run({"stage": 3, "mics_shard_size": 4})
        np.testing.assert_allclose(plain, mics, rtol=1e-4)


class TestTiledLinear:
    def test_matches_dense(self):
        from deepspeed_tpu.runtime.zero.tiling import TiledLinear

        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((4, 16)).astype(np.float32))
        tiled = TiledLinear(features=24, in_splits=4, out_splits=3)
        params = tiled.init(jax.random.PRNGKey(0), x)
        y = tiled.apply(params, x)
        kernel = params["params"]["kernel"]
        bias = params["params"]["bias"]
        expect = x @ kernel + bias
        np.testing.assert_allclose(np.asarray(y), np.asarray(expect),
                                   rtol=1e-4, atol=1e-5)

    def test_gradients_flow(self):
        from deepspeed_tpu.runtime.zero.tiling import TiledLinear

        x = jnp.ones((2, 8))
        tiled = TiledLinear(features=8, in_splits=2, out_splits=2,
                            use_bias=False)
        params = tiled.init(jax.random.PRNGKey(0), x)

        def loss(p):
            return jnp.sum(tiled.apply(p, x) ** 2)

        g = jax.grad(loss)(params)
        assert float(jnp.sum(jnp.abs(g["params"]["kernel"]))) > 0

    def test_split_divisibility_checked(self):
        from deepspeed_tpu.runtime.zero.tiling import TiledLinear

        x = jnp.ones((2, 10))
        tiled = TiledLinear(features=8, in_splits=3)
        with pytest.raises(AssertionError):
            tiled.init(jax.random.PRNGKey(0), x)
