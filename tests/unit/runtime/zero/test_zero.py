"""ZeRO stage correctness tests.

Analog of reference ``tests/unit/runtime/zero/test_zero.py``: each stage must
produce the same training trajectory as the stage-0 (plain DP) baseline, and
sharded state must actually be partitioned across the data axis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.runtime.zero.policy import ShardingRules, zero_shard_spec
from tests.unit.simple_model import SimpleModel, base_config, random_batch


def _train(stage, dtype="fp32", steps=5, gas=1, seed=7):
    model = SimpleModel(hidden_dim=16)
    cfg = base_config(stage=stage, dtype=dtype, micro=2, gas=gas)
    # tiny test params would all fall under the stage-3 persistence threshold
    cfg["zero_optimization"]["stage3_param_persistence_threshold"] = 0
    cfg["seed"] = seed
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    losses = []
    for i in range(steps):
        batch = random_batch(16 * gas, seed=i)
        losses.append(float(engine.train_batch(batch=batch)))
    return losses, engine


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stage_matches_stage0(stage):
    base, _ = _train(0)
    z, _ = _train(stage)
    assert np.allclose(base, z, rtol=1e-4, atol=1e-5), f"{base} vs {z}"


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_bf16_matches_stage0(stage):
    base, _ = _train(0, dtype="bf16")
    z, _ = _train(stage, dtype="bf16")
    assert np.allclose(base, z, rtol=2e-2, atol=1e-3), f"{base} vs {z}"


def test_zero_gas_matches_single(capfd):
    l1, _ = _train(1, gas=2, steps=3)
    assert all(np.isfinite(l1))


def test_master_state_is_sharded():
    _, engine = _train(1, dtype="bf16", steps=1)
    # master params must be partitioned over the data axis
    leaves = jax.tree_util.tree_leaves(engine.state["master"])
    big = max(leaves, key=lambda x: x.size)
    shard_shape = big.sharding.shard_shape(big.shape)
    assert np.prod(shard_shape) < big.size, "master not sharded"


def test_stage3_params_sharded():
    _, engine = _train(3, dtype="bf16", steps=1)
    leaves = jax.tree_util.tree_leaves(engine.state["params"])
    big = max(leaves, key=lambda x: x.size)
    shard_shape = big.sharding.shard_shape(big.shape)
    assert np.prod(shard_shape) < big.size, "stage-3 params not sharded"


def test_stage0_params_replicated():
    _, engine = _train(0, steps=1)
    for leaf in jax.tree_util.tree_leaves(engine.state["params"]):
        assert leaf.sharding.is_fully_replicated


def test_zero_shard_spec_picks_largest_free_dim(eight_device_mesh):
    from jax.sharding import PartitionSpec as P

    spec = zero_shard_spec((128, 64), eight_device_mesh, stage_applies=True)
    assert spec == P(("data", "expert", "seq"), None)
    # TP takes dim0 → zero shards dim1
    spec = zero_shard_spec((128, 64), eight_device_mesh, stage_applies=True,
                           tp_spec=P("model", None))
    assert spec == P("model", ("data", "expert", "seq"))


def test_zero_shard_spec_respects_persistence_threshold(eight_device_mesh):
    from jax.sharding import PartitionSpec as P

    spec = zero_shard_spec((8,), eight_device_mesh, stage_applies=True,
                           persistence_threshold=100)
    assert spec == P(None)


def test_indivisible_dim_stays_replicated(eight_device_mesh):
    from jax.sharding import PartitionSpec as P

    spec = zero_shard_spec((7, 3), eight_device_mesh, stage_applies=True)
    assert spec == P(None, None)
