"""ZeRO-Offload / ZeRO-Infinity tier tests.

- native CPU Adam numerics vs the device FusedAdam (tolerance 1e-5)
- AIO roundtrip incl. offsets + async overlap
- engine with offload_optimizer device=cpu: losses match the fused
  on-device run (same seed/data); device=nvme: same + state files on disk
- checkpoint save/load round-trips the offloaded optimizer state
"""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel import initialize_mesh, reset_mesh


def test_cpu_adam_matches_fused_adam():
    from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
    from deepspeed_tpu.ops.optimizers import fused_adam

    rng = np.random.default_rng(0)
    n = 4097  # off-alignment size
    p0 = rng.standard_normal(n).astype(np.float32)
    grads = [rng.standard_normal(n).astype(np.float32) for _ in range(5)]

    # device reference
    opt = fused_adam(weight_decay=0.01)
    params = jnp.asarray(p0)
    state = opt.init(params)
    for i, g in enumerate(grads):
        params, state = opt.update(jnp.asarray(g), state, params,
                                   jnp.asarray(1e-3), jnp.asarray(i))
    # host CPU Adam
    cpu = DeepSpeedCPUAdam(lr=1e-3, weight_decay=0.01)
    p = p0.copy()
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    for i, g in enumerate(grads):
        cpu.step(p, g.copy(), m, v, step_num=i + 1)
    np.testing.assert_allclose(p, np.asarray(params), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(m, np.asarray(state.exp_avg), rtol=1e-5, atol=1e-6)


def test_cpu_adam_numpy_fallback_matches_native():
    from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam

    native = DeepSpeedCPUAdam(lr=2e-3, weight_decay=0.1)
    fallback = DeepSpeedCPUAdam(lr=2e-3, weight_decay=0.1)
    fallback._lib = None
    if not native.native:
        pytest.skip("native build unavailable")
    rng = np.random.default_rng(1)
    p1 = rng.standard_normal(1000).astype(np.float32)
    p2 = p1.copy()
    g = rng.standard_normal(1000).astype(np.float32)
    m1 = np.zeros(1000, np.float32); v1 = np.zeros(1000, np.float32)
    m2 = np.zeros(1000, np.float32); v2 = np.zeros(1000, np.float32)
    native.step(p1, g.copy(), m1, v1, 1)
    fallback.step(p2, g.copy(), m2, v2, 1)
    np.testing.assert_allclose(p1, p2, rtol=1e-6, atol=1e-7)


def test_aio_roundtrip_with_offsets():
    from deepspeed_tpu.ops.aio import AioHandle

    h = AioHandle(num_threads=3)
    d = tempfile.mkdtemp()
    path = os.path.join(d, "blob.bin")
    a = np.arange(1024, dtype=np.float32)
    b = np.arange(1024, 2048, dtype=np.float32)
    h.async_pwrite(a, path, offset=0)
    h.async_pwrite(b, path, offset=a.nbytes)
    h.wait()
    out = np.empty(2048, np.float32)
    h.async_pread(out[:1024], path, offset=0)
    h.async_pread(out[1024:], path, offset=a.nbytes)
    h.wait()
    np.testing.assert_array_equal(out, np.arange(2048, dtype=np.float32))
    h.close()


def _offload_losses(offload_cfg, steps=5, dtype=jnp.float32):
    reset_mesh()
    mesh = initialize_mesh()
    cfg = GPT2Config(vocab_size=128, n_positions=32, n_embd=32, n_layer=2,
                     n_head=4, dtype=dtype)
    zero = {"stage": 2}
    if offload_cfg:
        zero["offload_optimizer"] = offload_cfg
    engine, _, _, _ = ds.initialize(
        model=GPT2LMHeadModel(cfg),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 2,
            "zero_optimization": zero,
            "optimizer": {"type": "Adam",
                          "params": {"lr": 1e-3, "weight_decay": 0.01}},
            "gradient_clipping": 1.0,
        })
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(steps):
        batch = {"input_ids": rng.integers(
            0, 128, (engine.train_batch_size(), 32)).astype(np.int32)}
        losses.append(float(engine.train_batch(batch=batch)))
    return losses, engine


def test_offload_cpu_matches_fused():
    base, _ = _offload_losses(None)
    off, eng = _offload_losses({"device": "cpu"})
    np.testing.assert_allclose(off, base, rtol=2e-4, atol=2e-4)
    assert eng._offload_opt is not None


def test_offload_nvme_matches_fused(tmp_path):
    base, _ = _offload_losses(None)
    off, eng = _offload_losses({"device": "nvme", "nvme_path": str(tmp_path)})
    np.testing.assert_allclose(off, base, rtol=2e-4, atol=2e-4)
    files = os.listdir(tmp_path)
    assert any(f.endswith(".m.bin") for f in files)
    assert any(f.endswith(".master.bin") for f in files)
    # state swapped out between steps: host arrays are released
    assert all(a is None for p, a in eng._offload_opt.m.items()
               if eng._offload_opt._float[p])


def test_offload_checkpoint_roundtrip(tmp_path):
    off, eng = _offload_losses({"device": "cpu"}, steps=3)
    eng.save_checkpoint(str(tmp_path))
    off2, eng2 = _offload_losses({"device": "cpu"}, steps=1)
    eng2.load_checkpoint(str(tmp_path))
    rng = np.random.default_rng(7)
    batch = {"input_ids": rng.integers(
        0, 128, (eng.train_batch_size(), 32)).astype(np.int32)}
    l1 = float(eng.train_batch(batch=batch))
    l2 = float(eng2.train_batch(batch=batch))
    assert abs(l1 - l2) < 1e-4
