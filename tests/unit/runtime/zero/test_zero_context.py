"""zero.Init / GatheredParameters contexts — analog of reference
``tests/unit/runtime/zero/test_zero_context*.py``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu import zero


def test_init_meta_construction():
    import flax.linen as nn

    class Big(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(64)(x)

    with zero.Init(dtype=jnp.bfloat16) as ctx:
        shapes = ctx.abstract_init(Big(), jnp.ones((1, 32)))
    k = shapes["params"]["Dense_0"]["kernel"]
    assert isinstance(k, jax.ShapeDtypeStruct)
    assert k.shape == (32, 64) and k.dtype == jnp.bfloat16


def test_init_disabled_is_noop():
    with zero.Init(enabled=False):
        x = jnp.ones((4,))  # concrete construction still works
    assert float(x.sum()) == 4


def _engine():
    from deepspeed_tpu.parallel import mesh as mesh_mod
    from tests.unit.simple_model import SimpleModel

    mesh_mod.reset_mesh()
    config = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 0.0}},
        "zero_optimization": {"stage": 3},
        "bf16": {"enabled": True},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                    config=config)
    return engine


def test_gathered_parameters_read_and_modify():
    from tests.unit.simple_model import random_batch

    engine = _engine()
    b = random_batch(engine.train_batch_size())
    engine.train_batch(batch=b)

    with zero.GatheredParameters(engine, modifier_rank=0) as params:
        assert params["linear_0"]["kernel"].shape == (16, 16)
        params["linear_0"]["kernel"] = np.zeros((16, 16), np.float32)

    host = jax.device_get(engine.state["params"]["linear_0"]["kernel"])
    np.testing.assert_array_equal(np.asarray(host, np.float32), 0.0)
    # master updated too → lr=0 training keeps the edit
    engine.train_batch(batch=b)
    host = jax.device_get(engine.state["params"]["linear_0"]["kernel"])
    np.testing.assert_array_equal(np.asarray(host, np.float32), 0.0)


def test_gathered_parameters_readonly():
    from tests.unit.simple_model import random_batch

    engine = _engine()
    engine.train_batch(batch=random_batch(engine.train_batch_size()))
    before = np.asarray(jax.device_get(
        engine.state["params"]["linear_0"]["kernel"]), np.float32)
    with zero.GatheredParameters(engine, modifier_rank=None) as params:
        params["linear_0"]["kernel"] = np.ones((16, 16), np.float32)
    after = np.asarray(jax.device_get(
        engine.state["params"]["linear_0"]["kernel"]), np.float32)
    np.testing.assert_array_equal(before, after)  # not written back
