"""1F1B executor: parity vs GPipe and TrainSchedule-semantics conformance.

The executed tick plan (one_f_one_b.py) must agree with the instruction
streams ``TrainSchedule`` generates (the reference's executable spec,
deepspeed/runtime/pipe/schedule.py:189-257): per-stage forward/backward
micro order, the F-before-B dependency chain, and the last stage's F(m)/B(m)
alternation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.gpt2 import GPT2Config
from deepspeed_tpu.models.gpt2_pipe import gpt2_pipe_module
from deepspeed_tpu.parallel import initialize_mesh, reset_mesh
from deepspeed_tpu.runtime.pipe.schedule import (
    BackwardPass,
    ForwardPass,
    TrainSchedule,
)


def _train(schedule, steps=2, gas=4, stages=2, zero_stage=0, fp16=False):
    reset_mesh()
    initialize_mesh(data=8 // stages, pipe=stages)
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=4,
                     n_head=2, dtype=jnp.float32)
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "zero_optimization": {"stage": zero_stage},
        "pipeline": {"schedule": schedule},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }
    if fp16:
        config["fp16"] = {"enabled": True, "loss_scale": 128.0}
    eng, _, _, _ = ds.initialize(model=gpt2_pipe_module(cfg, num_stages=stages),
                                 config=config)
    rng = np.random.default_rng(11)
    losses = []
    for _ in range(steps):
        batch = {"input_ids": rng.integers(
            0, 64, (eng.train_batch_size(), 32), dtype=np.int32)}
        losses.append(float(eng.train_batch(batch=batch)))
    return losses, jax.device_get(eng.state["params"])


def _max_param_diff(a, b):
    return max(jax.tree_util.tree_leaves(jax.tree_util.tree_map(
        lambda x, y: float(np.max(np.abs(np.asarray(x, np.float64) -
                                         np.asarray(y, np.float64)))), a, b)))


def test_1f1b_matches_gpipe_loss_and_params():
    """Same data, same init: the interleaved executor must reproduce the
    GPipe trajectory (identical math, different schedule)."""
    l_g, p_g = _train("gpipe")
    l_f, p_f = _train("1f1b")
    np.testing.assert_allclose(l_f, l_g, rtol=1e-5, atol=1e-5)
    assert _max_param_diff(p_g, p_f) < 1e-3


def test_1f1b_matches_gpipe_gas_2x_stages():
    """VERDICT done-criterion: parity at gas >= 2 x stages."""
    l_g, _ = _train("gpipe", steps=1, gas=8, stages=4)
    l_f, _ = _train("1f1b", steps=1, gas=8, stages=4)
    np.testing.assert_allclose(l_f, l_g, rtol=1e-5, atol=1e-5)


def test_1f1b_with_zero1_and_fp16():
    losses, _ = _train("1f1b", steps=3, zero_stage=1, fp16=True)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_unknown_schedule_rejected():
    # Literal["1f1b","gpipe"] → pydantic rejects at config-parse time
    with pytest.raises(Exception, match="schedule|1f1b|literal_error"):
        _train("bogus", steps=1)


# ---------------------------------------------------------------------------
# schedule-semantics conformance
# ---------------------------------------------------------------------------

def _executor_ticks(M, S):
    """Per-stage per-tick ops [('F'|'B', micro), ...] built from the SAME
    index functions the executor's scan body consumes."""
    from deepspeed_tpu.runtime.pipe.one_f_one_b import (
        backward_micro_ids,
        forward_micro_ids,
        total_ticks,
    )

    stage_ids = np.arange(S)
    ticks = {s: [] for s in range(S)}
    for t in range(total_ticks(M, S)):
        f_ids = forward_micro_ids(t, stage_ids, S)
        b_ids = backward_micro_ids(t, stage_ids, S)
        for s in range(S):
            ops = []
            if 0 <= f_ids[s] < M:
                ops.append(("F", int(f_ids[s])))
            if 0 <= b_ids[s] < M:
                ops.append(("B", int(b_ids[s])))
            ticks[s].append(ops)
    return ticks


def _executor_plan(M, S):
    ticks = _executor_ticks(M, S)
    return {s: [op for tick in ticks[s] for op in tick] for s in range(S)}


def _schedule_plan(M, S):
    """Per-stage ('F'|'B', micro) order from the TrainSchedule streams."""
    plan = {}
    for s in range(S):
        sched = TrainSchedule(micro_batches=M, stages=S, stage_id=s)
        seq = []
        for step_id, cmds in enumerate(sched.steps()):
            micro, is_fwd = sched._step_to_micro_batch(step_id)
            for cmd in cmds:
                if isinstance(cmd, ForwardPass):
                    seq.append(("F", micro))
                elif isinstance(cmd, BackwardPass):
                    seq.append(("B", micro))
        plan[s] = seq
    return plan


@pytest.mark.parametrize("M,S", [(4, 2), (8, 4), (4, 4), (2, 4), (6, 3)])
def test_executor_order_matches_train_schedule(M, S):
    """Per-stage forward micro order and backward micro order equal the
    TrainSchedule streams. (Exact F/B interleaving differs by at most the
    within-pair order on odd stages — the executor packs one F and one B per
    tick, the reference alternates one op per step; the dependency test
    below pins the semantics that matter.)"""
    ex, ref = _executor_plan(M, S), _schedule_plan(M, S)
    for s in range(S):
        assert [op for op in ex[s] if op[0] == "F"] == \
            [op for op in ref[s] if op[0] == "F"]
        assert [op for op in ex[s] if op[0] == "B"] == \
            [op for op in ref[s] if op[0] == "B"]
    # last stage alternates F(m), B(m) — the 1F1B signature
    last = ex[S - 1]
    for m in range(M):
        assert ("F", m) in last and ("B", m) in last
        assert last.index(("B", m)) == last.index(("F", m)) + 1


@pytest.mark.parametrize("M,S", [(8, 4), (4, 2), (4, 4)])
def test_executor_dependencies(M, S):
    """From the BUILT tick plan: B(m) at stage s happens at/after F(m) at
    stage s, after F(m) at the last stage (the loss), and exactly one tick
    after B(m) at stage s+1 (the cotangent producer)."""
    ticks = _executor_ticks(M, S)

    def tick_of(s, op):
        for t, ops in enumerate(ticks[s]):
            if op in ops:
                return t
        raise AssertionError(f"{op} never executed on stage {s}")

    for s in range(S):
        for m in range(M):
            tb = tick_of(s, ("B", m))
            assert tb >= tick_of(s, ("F", m))
            assert tb >= tick_of(S - 1, ("F", m))
            if s + 1 < S:
                assert tb == tick_of(s + 1, ("B", m)) + 1


def test_tick_count_packs_tighter_than_reference_steps():
    """Executor ticks (1F+1B each) = M + 2(S-1) vs the reference's
    2(M+S-1) single-op steps — the same schedule packed two ops per tick."""
    for M, S in [(4, 2), (8, 4)]:
        assert M + 2 * (S - 1) <= 2 * (M + S - 1)
