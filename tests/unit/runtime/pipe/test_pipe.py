"""Pipeline tests (analog of reference tests/unit/runtime/pipe/test_pipe.py
and pipe/test_pipe_module.py): schedule correctness, partitioning, and the
SPMD pipeline trajectory vs a non-pipelined baseline."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.parallel import initialize_mesh
from deepspeed_tpu.runtime.pipe.module import (
    LayerSpec,
    PipelineModule,
    partition_balanced,
)
from deepspeed_tpu.runtime.pipe.schedule import (
    BackwardPass,
    ForwardPass,
    InferenceSchedule,
    OptimizerStep,
    TrainSchedule,
)


# ---------------------------------------------------------------------------
# schedules
# ---------------------------------------------------------------------------
def test_train_schedule_executes_all_micros():
    for stages in (2, 4):
        for micros in (4, 8):
            for stage_id in range(stages):
                sched = TrainSchedule(micro_batches=micros, stages=stages,
                                      stage_id=stage_id)
                steps = sched.steps()
                fwd = [c for step in steps for c in step if isinstance(c, ForwardPass)]
                bwd = [c for step in steps for c in step if isinstance(c, BackwardPass)]
                assert len(fwd) == micros, f"stage {stage_id}: {len(fwd)} fwds"
                assert len(bwd) == micros
                opt = [c for step in steps for c in step if isinstance(c, OptimizerStep)]
                assert len(opt) == 1


def test_train_schedule_1f1b_interleave():
    """In steady state a stage alternates forward and backward."""
    sched = TrainSchedule(micro_batches=8, stages=2, stage_id=0)
    kinds = []
    for step in sched.steps():
        for c in step:
            if isinstance(c, (ForwardPass, BackwardPass)):
                kinds.append("F" if isinstance(c, ForwardPass) else "B")
    s = "".join(kinds)
    assert "FBFB" in s, s  # 1F1B steady state


def test_inference_schedule_tick_count():
    sched = InferenceSchedule(micro_batches=4, stages=4, stage_id=0)
    assert len(sched.steps()) == 4 + 4 - 1  # M + S - 1, the SPMD loop's ticks


def test_partition_balanced():
    parts = partition_balanced([1, 1, 1, 1], 2)
    assert parts == [0, 2, 4]
    parts = partition_balanced([10, 1, 1, 10], 2)
    assert parts == [0, 2, 4] or parts[1] in (1, 2, 3)
    # heavy first item
    parts = partition_balanced([100, 1, 1, 1], 2)
    assert parts[1] == 1


# ---------------------------------------------------------------------------
# SPMD pipeline module
# ---------------------------------------------------------------------------
class ToyEmbed(nn.Module):
    dim: int = 16

    @nn.compact
    def __call__(self, batch):
        return nn.Dense(self.dim, name="proj")(batch["x"])


class ToyBlock(nn.Module):
    dim: int = 16

    @nn.compact
    def __call__(self, x):
        return x + 0.1 * nn.Dense(self.dim, name="fc")(nn.tanh(x))


def _toy_loss(out, micro_batch):
    return jnp.mean((out.sum(-1) - micro_batch["y"]) ** 2)


def _pipe_model(n_blocks=4, stages=2):
    return PipelineModule(
        layers=tuple([LayerSpec(ToyEmbed)] + [LayerSpec(ToyBlock)] * n_blocks),
        loss_fn=_toy_loss,
        num_stages=stages,
    )


def _batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    return {"x": rng.normal(size=(n, 8)).astype(np.float32),
            "y": rng.normal(size=(n,)).astype(np.float32)}


def test_pipeline_trains():
    mesh = initialize_mesh(data=4, pipe=2)
    model = _pipe_model()
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 4,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "steps_per_print": 100}, mesh=mesh)
    losses = [float(engine.train_batch(batch=_batch())) for _ in range(6)]
    assert losses[-1] < losses[0], losses


def test_pipeline_matches_sequential():
    """Pipelined loss/trajectory must equal running the same stack densely."""

    class DenseModel(nn.Module):
        n_blocks: int = 4

        @nn.compact
        def __call__(self, stacked, deterministic=True):
            embed = ToyEmbed(name="embed")
            blocks = [ToyBlock(name=f"b{i}") for i in range(self.n_blocks)]

            def one_micro(mb):
                x = embed(mb)
                for block in blocks:
                    x = block(x)
                return _toy_loss(x, mb)

            # unrolled per-micro (module calls inside jax.vmap trip flax's
            # trace-level check; M is tiny and static)
            M = jax.tree_util.tree_leaves(stacked)[0].shape[0]
            micro = lambda i: jax.tree_util.tree_map(  # noqa: E731
                lambda x: x[i], stacked)
            return jnp.mean(jnp.stack([one_micro(micro(i))
                                       for i in range(M)]))

    # pipeline over 2 stages
    mesh = initialize_mesh(data=4, pipe=2)
    pipe_engine, _, _, _ = ds.initialize(model=_pipe_model(), config={
        "train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 2,
        "optimizer": {"type": "sgd", "params": {"lr": 1e-2}}, "seed": 3,
        "steps_per_print": 100}, mesh=mesh)
    pipe_losses = [float(pipe_engine.train_batch(batch=_batch(16))) for _ in range(4)]

    # the same architecture without pipelining can't share init RNGs across
    # differently-structured modules, so compare loss *dynamics* shape only:
    # both must strictly decrease with the same lr on the same data
    from deepspeed_tpu.parallel import reset_mesh

    reset_mesh()
    mesh2 = initialize_mesh(data=8)
    dense_engine, _, _, _ = ds.initialize(model=DenseModel(), config={
        "train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 2,
        "optimizer": {"type": "sgd", "params": {"lr": 1e-2}}, "seed": 3,
        "steps_per_print": 100}, mesh=mesh2)
    # dense model consumes the same stacked (M, mb, ...) layout
    dense_losses = [float(dense_engine.train_batch(batch=_batch(16)))
                    for _ in range(4)]
    assert pipe_losses[-1] < pipe_losses[0]
    assert dense_losses[-1] < dense_losses[0]
    # same starting loss scale (architectures identical up to init rng)
    assert abs(pipe_losses[0] - dense_losses[0]) / dense_losses[0] < 1.0


def test_pipeline_block_params_sharded_over_pipe():
    mesh = initialize_mesh(data=4, pipe=2)
    engine, _, _, _ = ds.initialize(model=_pipe_model(), config={
        "train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 4,
        "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
        "steps_per_print": 100}, mesh=mesh)
    engine.train_batch(batch=_batch())
    flat = jax.tree_util.tree_leaves_with_path(engine.state["params"])
    block_leaves = [(p, l) for p, l in flat
                    if "blocks" in "/".join(str(x) for x in p)]
    assert block_leaves
    for path, leaf in block_leaves:
        # dim0 = stage dim, sharded over pipe (2)
        assert leaf.shape[0] == 2
        assert leaf.sharding.shard_shape(leaf.shape)[0] == 1, \
            f"{path} not sharded over pipe"


def test_pipeline_rejects_heterogeneous():
    class Other(nn.Module):
        @nn.compact
        def __call__(self, x):
            return x

    specs = tuple([LayerSpec(ToyEmbed)] + [LayerSpec(ToyBlock), LayerSpec(Other)] * 2)
    model = PipelineModule(layers=specs, loss_fn=_toy_loss, num_stages=4)
    mesh = initialize_mesh(data=2, pipe=4)
    with pytest.raises(ValueError, match="homogeneous"):
        ds.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 4,
            "steps_per_print": 100}, mesh=mesh)[0].train_batch(batch=_batch(8))


def test_pipeline_forward_raises():
    mesh = initialize_mesh(data=4, pipe=2)
    engine, _, _, _ = ds.initialize(model=_pipe_model(), config={
        "train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 4,
        "steps_per_print": 100}, mesh=mesh)
    with pytest.raises(RuntimeError, match="train_batch"):
        engine.forward(_batch())


def test_pipeline_tied_head_shares_params():
    """TiedLayerSpec: embedding reused as head must NOT create a second
    parameter set (reference TiedLayerSpec, module.py:76)."""
    from deepspeed_tpu.runtime.pipe.module import TiedLayerSpec

    class Emb(nn.Module):
        dim: int = 16

        @nn.compact
        def __call__(self, batch_or_x):
            d = nn.Dense(self.dim, name="w")
            if isinstance(batch_or_x, dict):
                return d(batch_or_x["x"])
            return d(batch_or_x)

    def head_fwd(module, x):
        return module(x)  # reuse the same tied module

    def loss(out, mb):
        return jnp.mean((out.sum(-1) - mb["y"]) ** 2)

    specs = tuple([TiedLayerSpec("emb", Emb)] + [LayerSpec(ToyBlock)] * 2
                  + [TiedLayerSpec("emb", Emb, forward_fn=head_fwd)])
    mesh = initialize_mesh(data=4, pipe=2)
    model = PipelineModule(layers=specs, loss_fn=loss, num_stages=2)
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 4,
        "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
        "steps_per_print": 100}, mesh=mesh)
    # feature dim 16 on both sides so the tied Dense serves embed AND head
    rng = np.random.default_rng(0)
    batch16 = {"x": rng.normal(size=(16, 16)).astype(np.float32),
               "y": rng.normal(size=(16,)).astype(np.float32)}
    engine.train_batch(batch=batch16)
    paths = ["/".join(str(x) for x in p)
             for p, _ in jax.tree_util.tree_leaves_with_path(engine.state["params"])]
    tied = [p for p in paths if "tied_emb" in p]
    post = [p for p in paths if "post_" in p]
    assert tied, paths
    assert not post, f"tied head created independent params: {post}"


def test_pipeline_transformer_block_layerspec():
    """The REAL TransformerBlock — signature (x, decode, deterministic,
    kv_cache, block_hint), returning (x, new_cache) — must work as a
    LayerSpec block: the executors detect the decode_det call mode and
    unpack the tuple return."""
    from deepspeed_tpu.models.transformer_lm import (TransformerBlock,
                                                     TransformerConfig)

    cfg = TransformerConfig(vocab_size=64, max_seq_len=16, n_embd=32,
                            n_layer=4, n_head=4, dtype=jnp.float32)

    class TokEmbed(nn.Module):
        @nn.compact
        def __call__(self, batch):
            return nn.Embed(cfg.vocab_size, cfg.n_embd,
                            name="tok")(batch["input_ids"])

    def lm_loss(out, mb):
        return jnp.mean((out.mean(axis=(-1, -2)) - mb["y"]) ** 2)

    specs = tuple([LayerSpec(TokEmbed)]
                  + [LayerSpec(TransformerBlock, cfg)] * 4)
    mesh = initialize_mesh(data=4, pipe=2)
    model = PipelineModule(layers=specs, loss_fn=lm_loss, num_stages=2)
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 4,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "steps_per_print": 100}, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 64, (16, 8)).astype(np.int32),
             "y": rng.normal(size=(16,)).astype(np.float32)}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(5)]
    assert np.all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_pipeline_eval_batch():
    mesh = initialize_mesh(data=4, pipe=2)
    engine, _, _, _ = ds.initialize(model=_pipe_model(), config={
        "train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 4,
        "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
        "steps_per_print": 100}, mesh=mesh)
    loss = engine.eval_batch(batch=_batch())
    assert np.isfinite(float(loss))
