"""3D parallelism (ZeRO-DP × PP × TP) on GPT-2 — the Megatron-GPT parity
config (analog of reference tests/unit/model_parallelism)."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu as ds
from deepspeed_tpu.models.gpt2 import GPT2Config
from deepspeed_tpu.models.gpt2_pipe import gpt2_pipe_module, gpt2_pipe_sharding_rules
from deepspeed_tpu.parallel import initialize_mesh
from deepspeed_tpu.runtime.zero.policy import ShardingRules


def test_gpt2_3d_parallel_trains():
    """dp=2 × pp=2 × tp=2 on the virtual 8-device mesh, ZeRO-1 bf16."""
    mesh = initialize_mesh(data=2, model=2, pipe=2)
    cfg = GPT2Config(vocab_size=128, n_positions=32, n_embd=32, n_layer=4,
                     n_head=2, dtype=jnp.bfloat16)
    model = gpt2_pipe_module(cfg, num_stages=2)
    engine, _, _, _ = ds.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "gradient_clipping": 1.0,
            "steps_per_print": 100,
        },
        mesh=mesh,
        sharding_rules=ShardingRules(gpt2_pipe_sharding_rules()))

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 128, (8, 32)).astype(np.int32)}
    l0 = float(engine.train_batch(batch=batch))
    for _ in range(4):
        loss = engine.train_batch(batch=batch)
    assert float(loss) < l0, (l0, float(loss))

    # verify the composed sharding actually happened
    flat = jax.tree_util.tree_leaves_with_path(engine.state["params"])
    qkv = [(p, l) for p, l in flat if "qkv" in "/".join(str(x) for x in p)
           and "kernel" in "/".join(str(x) for x in p)]
    assert qkv
    for path, leaf in qkv:
        shard = leaf.sharding.shard_shape(leaf.shape)
        assert shard[0] == leaf.shape[0] // 2, f"{path}: stage dim not pipe-sharded"
        assert shard[-1] == leaf.shape[-1] // 2, f"{path}: out dim not tp-sharded"
    # master (ZeRO-1) sharded over data
    mflat = jax.tree_util.tree_leaves(engine.state["master"])
    big = max(mflat, key=lambda x: x.size)
    assert np.prod(big.sharding.shard_shape(big.shape)) < big.size
