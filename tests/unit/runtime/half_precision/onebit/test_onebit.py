"""1-bit optimizer + compressed collective tests — analog of reference
``tests/onebit/`` and ``tests/unit/runtime/comm`` compression suites."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds


def _quadratic_problem(n=32, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n)).astype(np.float32)
    A = A @ A.T / n + np.eye(n, dtype=np.float32)
    b = rng.standard_normal(n).astype(np.float32)

    def loss(params):
        x = params["x"]
        return 0.5 * x @ jnp.asarray(A) @ x - jnp.asarray(b) @ x

    x0 = {"x": jnp.zeros(n, jnp.float32)}
    return loss, x0


def _run_optimizer(opt_def, loss, params, steps, lr=0.05):
    state = opt_def.init(params)
    losses = []
    grad_fn = jax.jit(jax.grad(loss))
    for t in range(steps):
        g = grad_fn(params)
        params, state = opt_def.update(g, state, params,
                                       jnp.asarray(lr), jnp.asarray(t))
        losses.append(float(loss(params)))
    return params, losses


class TestOnebitAdam:
    def test_matches_adam_during_warmup(self):
        from deepspeed_tpu.ops.optimizers import get_optimizer

        loss, x0 = _quadratic_problem()
        adam = get_optimizer("adam", {})
        onebit = get_optimizer("onebitadam", {"freeze_step": 1000})
        _, l_adam = _run_optimizer(adam, loss, x0, 20)
        _, l_onebit = _run_optimizer(onebit, loss, x0, 20)
        np.testing.assert_allclose(l_adam, l_onebit, rtol=1e-5)

    def test_converges_after_freeze(self):
        from deepspeed_tpu.ops.optimizers import get_optimizer

        loss, x0 = _quadratic_problem()
        onebit = get_optimizer("onebitadam", {"freeze_step": 10})
        _, losses = _run_optimizer(onebit, loss, x0, 150, lr=0.02)
        assert losses[-1] < losses[10] < losses[0]

    def test_engine_accepts_onebit_adam(self):
        from tests.unit.simple_model import SimpleModel, random_batch

        config = {
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "OneBitAdam",
                          "params": {"lr": 1e-2, "freeze_step": 2}},
            "steps_per_print": 1000,
        }
        engine, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                        config=config)
        b = random_batch(engine.train_batch_size())
        losses = [float(engine.train_batch(batch=b)) for _ in range(8)]
        assert losses[-1] < losses[0], losses


class TestOnebitLamb:
    def test_converges(self):
        from deepspeed_tpu.ops.optimizers import get_optimizer

        loss, x0 = _quadratic_problem()
        lamb = get_optimizer("onebitlamb", {"freeze_step": 10})
        _, losses = _run_optimizer(lamb, loss, x0, 100, lr=0.02)
        assert losses[-1] < losses[0]


class TestZeroOneAdam:
    def test_converges(self):
        from deepspeed_tpu.ops.optimizers import get_optimizer

        loss, x0 = _quadratic_problem()
        zo = get_optimizer("zerooneadam", {"var_freeze_step": 50,
                                           "var_update_scaler": 4})
        _, losses = _run_optimizer(zo, loss, x0, 150, lr=0.02)
        assert losses[-1] < losses[0]


class TestCompressedAllreduce:
    def test_local_fallback_error_feedback(self):
        from deepspeed_tpu.runtime.comm import compressed_allreduce

        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal(64).astype(np.float32))
        we = jnp.zeros(64)
        se = jnp.zeros(64)
        out, we2, se2 = compressed_allreduce(x, we, se, axis_name=None)
        # out + error == input (lossless with feedback)
        np.testing.assert_allclose(np.asarray(out + we2), np.asarray(x),
                                   rtol=1e-5, atol=1e-5)

    def test_mesh_allreduce_approximates_mean(self):
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from deepspeed_tpu.runtime.comm import compressed_allreduce

        devs = np.array(jax.devices()[:8])
        mesh = Mesh(devs, ("dp",))
        n = 128  # per-device vector length, divisible by 8
        rng = np.random.default_rng(1)
        xs = rng.standard_normal((8, n)).astype(np.float32)
        true_mean = xs.mean(axis=0)

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P("dp"), P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp"), P("dp")))
        def run(x, we, se):
            out, we2, se2 = compressed_allreduce(
                x[0], we[0], se[0], axis_name="dp")
            return out[None], we2[None], se2[None]

        we = np.zeros((8, n), np.float32)
        se = np.zeros((8, n // 8), np.float32)  # per-rank server chunk
        # the error-feedback guarantee: the RUNNING SUM of outputs tracks
        # the running sum of inputs (Σ out_t ≈ t · mean), since the
        # leftover quantization error stays bounded in the feedback buffers
        acc = np.zeros(n, np.float32)
        T = 40
        est = None
        for _ in range(T):
            est, we, se = run(xs, we, se)
            acc += np.asarray(est)[0]
        est = np.asarray(est)
        # every device sees the same result
        for d in range(1, 8):
            np.testing.assert_allclose(est[d], est[0], rtol=1e-5)
        avg = acc / T
        err = np.linalg.norm(avg - true_mean) / np.linalg.norm(true_mean)
        assert err < 0.2, err

    def test_compression_ratio(self):
        """Signs travel as int8: 4x smaller than fp32 (plus tiny scales)."""
        x = np.zeros(1024, np.float32)
        signs = np.where(x >= 0, 1, -1).astype(np.int8)
        assert signs.nbytes * 4 == x.nbytes
