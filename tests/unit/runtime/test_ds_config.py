"""Config-system tests (analog of reference tests/unit/runtime/
test_ds_config_dict.py and test_ds_config_model.py)."""

import json

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.zero.config import ZeroStageEnum
from deepspeed_tpu.runtime.zero.offload_config import OffloadDeviceEnum


def test_batch_reconciliation_all_given():
    c = DeepSpeedConfig({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4,
                         "gradient_accumulation_steps": 1}, world_size=8)
    assert c.train_batch_size == 32


def test_batch_reconciliation_infer_gas():
    c = DeepSpeedConfig({"train_batch_size": 64, "train_micro_batch_size_per_gpu": 4},
                        world_size=8)
    assert c.gradient_accumulation_steps == 2


def test_batch_reconciliation_infer_train():
    c = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 4,
                         "gradient_accumulation_steps": 2}, world_size=4)
    assert c.train_batch_size == 32


def test_batch_reconciliation_micro_only():
    c = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 2}, world_size=8)
    assert c.train_batch_size == 16
    assert c.gradient_accumulation_steps == 1


def test_batch_invariant_violation():
    with pytest.raises(ValueError):
        DeepSpeedConfig({"train_batch_size": 33, "train_micro_batch_size_per_gpu": 4,
                         "gradient_accumulation_steps": 1}, world_size=8)


def test_no_batch_size_raises():
    with pytest.raises(ValueError):
        DeepSpeedConfig({}, world_size=8)


def test_zero_config_parse():
    c = DeepSpeedConfig({
        "train_batch_size": 8,
        "bf16": {"enabled": True},
        "zero_optimization": {
            "stage": 3,
            "reduce_bucket_size": 1000,
            "offload_optimizer": {"device": "cpu"},
            "stage3_prefetch_bucket_size": 500,
        },
    }, world_size=8)
    assert c.zero_optimization.stage == ZeroStageEnum.weights
    assert c.zero_optimization.offload_optimizer.device == OffloadDeviceEnum.cpu
    assert c.zero_optimization.reduce_bucket_size == 1000
    # stage-3 defaults overlap_comm on
    assert c.zero_optimization.overlap_comm is True


def test_deprecated_field_migration():
    c = DeepSpeedConfig({
        "train_batch_size": 8,
        "zero_optimization": {"stage3_gather_fp16_weights_on_model_save": True},
    }, world_size=8)
    assert c.zero_optimization.stage3_gather_16bit_weights_on_model_save is True


def test_fp16_bf16_exclusive():
    with pytest.raises(ValueError):
        DeepSpeedConfig({"train_batch_size": 8, "fp16": {"enabled": True},
                         "bf16": {"enabled": True}}, world_size=8)


def test_auto_values_dropped():
    c = DeepSpeedConfig({"train_batch_size": 8, "gradient_clipping": "auto"}, world_size=8)
    assert c.gradient_clipping == 0.0


def test_duplicate_keys_rejected(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
    with pytest.raises(ValueError):
        DeepSpeedConfig(str(p), world_size=8)


def test_config_from_file(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"train_batch_size": 16, "fp16": {"enabled": True}}))
    c = DeepSpeedConfig(str(p), world_size=8)
    assert c.fp16.enabled and c.train_batch_size == 16


def test_legacy_monitor_keys_fold_in():
    c = DeepSpeedConfig({"train_batch_size": 8,
                         "tensorboard": {"enabled": True, "output_path": "/tmp/tb"}},
                        world_size=8)
    assert c.monitor_config.tensorboard.enabled
    assert c.monitor_config.enabled
