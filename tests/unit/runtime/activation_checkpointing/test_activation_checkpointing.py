"""Activation-checkpointing tests (≅ reference
tests/unit/runtime/activation_checkpointing/test_activation_checkpointing.py:
checkpointed fwd/bwd must match the non-checkpointed graph exactly)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec
from jax.experimental.shard_map import shard_map

import deepspeed_tpu
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as ckpt


@pytest.fixture(autouse=True)
def _reset_config():
    ckpt.reset()
    yield
    ckpt.reset()


def _mlp(w):
    def f(x):
        h = jnp.tanh(x @ w)
        return jnp.sum(h * h)

    return f


def test_checkpoint_matches_uncheckpointed():
    ckpt.configure(deepspeed_config={
        "train_batch_size": 1,
        "activation_checkpointing": {"partition_activations": False},
    })
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (16, 16), dtype=jnp.float32)
    x = jax.random.normal(jax.random.fold_in(key, 1), (8, 16))

    f = _mlp(w)
    ref_val, ref_grad = jax.value_and_grad(f)(x)
    val, grad = jax.value_and_grad(lambda a: ckpt.checkpoint(f, a))(x)
    np.testing.assert_allclose(np.asarray(val), np.asarray(ref_val), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad), rtol=1e-6)


def test_configure_from_json_block():
    ckpt.configure(deepspeed_config={
        "train_batch_size": 1,
        "activation_checkpointing": {
            "partition_activations": True,
            "cpu_checkpointing": False,
            "number_checkpoints": 2,
        },
    })
    assert ckpt.is_configured()
    assert ckpt._CONFIG.partition_activations
    assert ckpt._CONFIG.num_checkpoints == 2


def test_checkpoint_sequential_segments():
    ckpt.configure(num_checkpoints=2)
    key = jax.random.PRNGKey(0)
    ws = [jax.random.normal(jax.random.fold_in(key, i), (8, 8)) / 3
          for i in range(5)]
    layers = [lambda h, w=w: jnp.tanh(h @ w) for w in ws]
    x = jax.random.normal(jax.random.fold_in(key, 99), (4, 8))

    def ref(h):
        for layer in layers:
            h = layer(h)
        return jnp.sum(h)

    def seq(h):
        return jnp.sum(ckpt.checkpoint_sequential(layers, h))

    ref_val, ref_grad = jax.value_and_grad(ref)(x)
    val, grad = jax.value_and_grad(seq)(x)
    np.testing.assert_allclose(np.asarray(val), np.asarray(ref_val), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad), rtol=1e-4, atol=1e-5)


def test_partition_activations_on_mesh():
    """partition_activations shards saved inputs over the model axis; the
    grads must be identical to the unpartitioned graph."""
    from deepspeed_tpu.parallel import initialize_mesh

    initialize_mesh(data=4, model=2)
    ckpt.configure(partition_activations=True)

    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (16, 16))
    x = jax.random.normal(jax.random.fold_in(key, 1), (8, 16))
    f = _mlp(w)

    @jax.jit
    def g(a):
        return jax.value_and_grad(lambda b: ckpt.checkpoint(f, b))(a)

    val, grad = g(x)
    ref_val, ref_grad = jax.value_and_grad(f)(x)
    np.testing.assert_allclose(np.asarray(val), np.asarray(ref_val), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad), rtol=1e-4, atol=1e-5)


def test_partition_helper_is_noop_without_model_axis():
    x = jnp.ones((6, 4))
    out = ckpt.partition(x)
    assert out.shape == x.shape


def test_rng_tracker_fork_and_seed():
    ckpt.model_parallel_manual_seed(1234, mp_rank=0)
    tracker = ckpt.get_rng_tracker()
    with tracker.fork() as k1:
        pass
    with tracker.fork() as k2:
        pass
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))

    ckpt.model_parallel_manual_seed(1234, mp_rank=1)
    with ckpt.get_rng_tracker().fork() as k3:
        pass
    assert not np.array_equal(np.asarray(k1), np.asarray(k3))


def test_fold_in_model_parallel_rank_differs_per_rank():
    from deepspeed_tpu.parallel import initialize_mesh

    mesh = initialize_mesh(data=4, model=2)
    key = jax.random.PRNGKey(7)

    def body(k):
        return ckpt.fold_in_model_parallel_rank(k)[None, :]

    keys = shard_map(
        body, mesh=mesh,
        in_specs=PartitionSpec(),
        out_specs=PartitionSpec("model"))(key)
    ks = np.asarray(jax.device_get(keys))
    assert not np.array_equal(ks[0], ks[1])


def test_cpu_checkpointing_offload_policy():
    """cpu_checkpointing: tagged activations are offloaded (policy path);
    numerics must be unchanged."""
    ckpt.configure(checkpoint_in_cpu=True)
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (16, 16))
    x = jax.random.normal(jax.random.fold_in(key, 1), (8, 16))

    def f(a):
        h = jnp.tanh(a @ w)
        h = ckpt.checkpoint_name(h, ckpt.OFFLOAD_NAME)
        return jnp.sum(h * (a @ w))

    try:
        val, grad = jax.jit(
            jax.value_and_grad(lambda a: ckpt.checkpoint(f, a)))(x)
    except Exception:
        pytest.skip("host offload memory space unsupported on this backend")
    ref_val, ref_grad = jax.value_and_grad(f)(x)
    np.testing.assert_allclose(np.asarray(val), np.asarray(ref_val), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad), rtol=1e-4, atol=1e-5)
