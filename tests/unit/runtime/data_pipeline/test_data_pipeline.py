"""Data-efficiency suite — analog of reference
``tests/unit/runtime/test_data_efficiency.py`` (curriculum + random-LTD)
and the data_sampling tests."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
    CurriculumScheduler,
)


class TestCurriculumScheduler:
    def test_fixed_linear(self):
        s = CurriculumScheduler({
            "min_difficulty": 8, "max_difficulty": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8},
        })
        assert s.update_difficulty(0) == 8
        mid = s.update_difficulty(50)
        assert 8 < mid < 64 and mid % 8 == 0
        assert s.update_difficulty(100) == 64
        assert s.update_difficulty(1000) == 64

    def test_fixed_root(self):
        s = CurriculumScheduler({
            "min_difficulty": 8, "max_difficulty": 64,
            "schedule_type": "fixed_root",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8, "root_degree": 2},
        })
        # sqrt schedule rises faster early than linear
        lin = CurriculumScheduler({
            "min_difficulty": 8, "max_difficulty": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8},
        })
        assert s.update_difficulty(25) >= lin.update_difficulty(25)

    def test_fixed_discrete(self):
        s = CurriculumScheduler({
            "min_difficulty": 2, "max_difficulty": 10,
            "schedule_type": "fixed_discrete",
            "schedule_config": {"difficulty": [2, 4, 10],
                                "max_step": [5, 10]},
        })
        assert s.update_difficulty(3) == 2
        assert s.update_difficulty(7) == 4
        assert s.update_difficulty(11) == 10

    def test_custom(self):
        s = CurriculumScheduler({
            "min_difficulty": 1, "max_difficulty": 100,
            "schedule_type": "custom",
        })
        s.set_custom_get_difficulty(lambda step: min(step * 2, 100))
        assert s.update_difficulty(10) == 20

    def test_state_roundtrip(self):
        s = CurriculumScheduler({
            "min_difficulty": 8, "max_difficulty": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8},
        })
        s.update_difficulty(42)
        sd = s.state_dict()
        s2 = CurriculumScheduler({
            "min_difficulty": 8, "max_difficulty": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8},
        })
        s2.load_state_dict(sd)
        assert s2.get_current_difficulty() == s.get_current_difficulty()


class TestIndexedDataset:
    def test_roundtrip(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_sampling import (
            MMapIndexedDataset,
            MMapIndexedDatasetBuilder,
        )

        prefix = str(tmp_path / "corpus")
        builder = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
        docs = [np.arange(n, dtype=np.int32) for n in (3, 7, 1, 12)]
        for d in docs:
            builder.add_item(d)
        builder.finalize()

        ds = MMapIndexedDataset(prefix)
        assert len(ds) == 4
        for i, d in enumerate(docs):
            np.testing.assert_array_equal(ds[i], d)
        np.testing.assert_array_equal(ds.sizes, [3, 7, 1, 12])
        # partial read
        np.testing.assert_array_equal(ds.get(3, offset=2, length=4),
                                      [2, 3, 4, 5])
        assert MMapIndexedDataset.exists(prefix)


class TestDataSampler:
    def _sampler(self, metric_values, difficulty_type="value"):
        from deepspeed_tpu.runtime.data_pipeline.data_sampling import (
            DeepSpeedDataSampler,
        )

        cfg = {
            "curriculum_learning": {
                "enabled": True,
                "curriculum_metrics": {
                    "seqlen": {
                        "min_difficulty": 2, "max_difficulty": 100,
                        "schedule_type": "fixed_linear",
                        "schedule_config": {"total_curriculum_step": 10,
                                            "difficulty_step": 2},
                        "difficulty_type": difficulty_type,
                    }
                },
            }
        }
        return DeepSpeedDataSampler(
            cfg, one_epoch_total_samples=len(metric_values),
            micro_batch_size=2, data_parallel_rank=0, data_parallel_size=2,
            metric_values={"seqlen": metric_values})

    def test_early_batches_are_easy(self):
        values = np.arange(100)  # difficulty == index
        sampler = self._sampler(values)
        it = iter(sampler)
        first = next(it)
        assert all(values[i] <= 4 for i in first), first

    def test_difficulty_grows(self):
        values = np.arange(100)
        sampler = self._sampler(values)
        batch = None
        for _ in range(2):  # difficulty carries across epochs
            for batch in sampler:
                pass
        assert any(values[i] > 10 for i in batch) or \
            sampler.current_difficulties["seqlen"] == 100

    def test_epoch_length(self):
        values = np.arange(10)
        sampler = self._sampler(values)  # global batch = 2*2 = 4
        assert len(list(iter(sampler))) == 2  # drop_last floors 10/4
        sampler.drop_last = False
        assert len(list(iter(sampler))) == 3

    def test_state_roundtrip(self):
        values = np.arange(50)
        sampler = self._sampler(values)
        it = iter(sampler)
        for _ in range(3):
            next(it)
        sd = sampler.state_dict()
        sampler2 = self._sampler(values)
        sampler2.load_state_dict(sd)
        assert sampler2.consumed_samples == sampler.consumed_samples
        np.testing.assert_array_equal(next(iter(sampler2)), next(it))


class TestRandomLTD:
    def test_sample_tokens_sorted_unique(self):
        import jax

        from deepspeed_tpu.ops.random_ltd import sample_tokens

        idx = sample_tokens(jax.random.PRNGKey(0), batch=4, seq_length=16,
                            reserved_length=8)
        assert idx.shape == (4, 8)
        idx = np.asarray(idx)
        for row in idx:
            assert (np.diff(row) > 0).all(), row  # sorted & unique
            assert row.min() >= 0 and row.max() < 16

    def test_gather_scatter_roundtrip(self):
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.ops.random_ltd import (
            gather_tokens,
            sample_tokens,
            scatter_tokens,
        )

        x = jnp.arange(2 * 8 * 4, dtype=jnp.float32).reshape(2, 8, 4)
        idx = sample_tokens(jax.random.PRNGKey(1), 2, 8, 3)
        part = gather_tokens(x, idx)
        assert part.shape == (2, 3, 4)
        out = scatter_tokens(x, part * 0, idx)
        # selected positions zeroed, others untouched
        out = np.asarray(out)
        xn = np.asarray(x)
        for b in range(2):
            for s in range(8):
                if s in np.asarray(idx)[b]:
                    assert (out[b, s] == 0).all()
                else:
                    np.testing.assert_array_equal(out[b, s], xn[b, s])

    def test_random_layer_token_drop_module(self):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.runtime.data_pipeline.data_routing import (
            RandomLayerTokenDrop,
        )

        layer = nn.Dense(4)
        wrapped = RandomLayerTokenDrop(layer=layer)
        x = jnp.ones((2, 8, 4))
        params = wrapped.init(
            {"params": jax.random.PRNGKey(0),
             "random_ltd": jax.random.PRNGKey(1)}, x, reserved_length=4)
        out = wrapped.apply(params, x, reserved_length=4,
                            rngs={"random_ltd": jax.random.PRNGKey(2)})
        assert out.shape == x.shape
        # deterministic mode = plain layer
        out_det = wrapped.apply(params, x, deterministic=True)
        assert out_det.shape == x.shape

    def test_token_drop_gathers_attention_mask(self):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.runtime.data_pipeline.data_routing import (
            RandomLayerTokenDrop,
        )

        class MaskChecker(nn.Module):
            @nn.compact
            def __call__(self, h, attention_mask=None):
                assert attention_mask is not None
                assert attention_mask.shape[-1] == h.shape[1], \
                    (attention_mask.shape, h.shape)
                return h

        wrapped = RandomLayerTokenDrop(layer=MaskChecker())
        x = jnp.ones((2, 8, 4))
        mask2d = jnp.ones((2, 8))
        mask4d = jnp.ones((2, 1, 8, 8))
        rngs = {"params": jax.random.PRNGKey(0),
                "random_ltd": jax.random.PRNGKey(1)}
        params = wrapped.init(rngs, x, reserved_length=4,
                              attention_mask=mask2d)
        out = wrapped.apply(params, x, reserved_length=4,
                            attention_mask=mask2d,
                            rngs={"random_ltd": jax.random.PRNGKey(2)})
        assert out.shape == x.shape
        out = wrapped.apply(params, x, reserved_length=4,
                            attention_mask=mask4d,
                            rngs={"random_ltd": jax.random.PRNGKey(2)})
        assert out.shape == x.shape

    def test_scheduler(self):
        from deepspeed_tpu.runtime.data_pipeline.data_routing import (
            RandomLTDScheduler,
        )

        s = RandomLTDScheduler({
            "random_ltd_schedule": {
                "min_value": 16, "max_value": 64,
                "schedule_type": "fixed_linear",
                "schedule_config": {"require_steps": 10, "seq_per_step": 8},
            }
        })
        assert s.update_seq(0) == 16
        assert s.update_seq(10) == 64
        v = s.update_seq(5)
        assert 16 <= v <= 64 and v % 8 == 0


def test_engine_curriculum_seqlen_truncation():
    """Curriculum seqlen truncates the batch early in training."""
    import flax.linen as nn
    import jax.numpy as jnp

    import deepspeed_tpu as ds

    seen_lens = []

    class LenProbe(nn.Module):
        @nn.compact
        def __call__(self, batch, deterministic=True):
            x = batch["input_ids"]
            seen_lens.append(x.shape[1])
            h = nn.Embed(50, 8)(x)
            return jnp.mean(h ** 2)

    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "curriculum_learning": {
            "enabled": True, "curriculum_type": "seqlen",
            "min_difficulty": 4, "max_difficulty": 16,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 4,
                                "difficulty_step": 4},
        },
        "steps_per_print": 1000,
    }
    engine, _, _, _ = ds.initialize(model=LenProbe(), config=config)
    rng = np.random.default_rng(0)

    def batch():
        return {"input_ids": rng.integers(
            0, 50, (engine.train_batch_size(), 16)).astype(np.int32)}

    for _ in range(6):
        engine.train_batch(batch=batch())
    assert min(seen_lens) <= 8, seen_lens   # truncated early
    assert max(seen_lens) == 16, seen_lens  # full length by the end


# ---------------------------------------------------------------------------
# round 2: offline data analyzer (reference data_analyzer.py analog)
# ---------------------------------------------------------------------------
class TestDataAnalyzer:
    def _dataset(self, n=40):
        rng = np.random.default_rng(0)
        # variable-length "token" samples: seqlen is the natural difficulty
        return [rng.integers(0, 100, rng.integers(4, 32)).tolist()
                for _ in range(n)]

    def test_map_reduce_single_worker(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_sampling import (
            DataAnalyzer,
        )

        ds = self._dataset()
        analyzer = DataAnalyzer(
            ds, {"seqlen": len, "vocab_max": lambda s: max(s)},
            save_path=str(tmp_path), num_threads=2, batch_size=8)
        merged = analyzer.run_map_reduce()
        np.testing.assert_array_equal(merged["seqlen"],
                                      [len(s) for s in ds])
        np.testing.assert_array_equal(merged["vocab_max"],
                                      [max(s) for s in ds])
        # persisted artifacts load back identically
        loaded = DataAnalyzer.load_metric_values(str(tmp_path), "seqlen")
        np.testing.assert_array_equal(loaded, merged["seqlen"])
        import json as _json

        meta = _json.load(open(tmp_path / "seqlen_meta.json"))
        assert meta["count"] == len(ds)
        assert meta["min"] == min(len(s) for s in ds)
        m2s = _json.load(open(tmp_path / "seqlen_metric_to_sample.json"))
        # every sample id appears exactly once across the value buckets
        all_ids = sorted(i for ids in m2s.values() for i in ids)
        assert all_ids == list(range(len(ds)))

    def test_multi_worker_shards_merge(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_sampling import (
            DataAnalyzer,
        )

        ds = self._dataset(31)
        for w in range(3):
            DataAnalyzer(ds, {"seqlen": len}, save_path=str(tmp_path),
                         num_workers=3, worker_id=w).run_map()
        merged = DataAnalyzer(ds, {"seqlen": len}, save_path=str(tmp_path),
                              num_workers=3).run_reduce()
        np.testing.assert_array_equal(merged["seqlen"],
                                      [len(s) for s in ds])

    def test_reduce_missing_shard_raises(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_sampling import (
            DataAnalyzer,
        )

        ds = self._dataset(10)
        DataAnalyzer(ds, {"seqlen": len}, save_path=str(tmp_path),
                     num_workers=2, worker_id=0).run_map()
        with pytest.raises(FileNotFoundError):
            DataAnalyzer(ds, {"seqlen": len}, save_path=str(tmp_path),
                         num_workers=2).run_reduce()

    def test_sampler_loads_analyzer_index(self, tmp_path):
        """The curriculum sampler auto-loads the analyzer's
        sample_to_metric index from the configured path."""
        from deepspeed_tpu.runtime.data_pipeline.data_sampling import (
            DataAnalyzer,
            DeepSpeedDataSampler,
        )

        ds = self._dataset(32)
        DataAnalyzer(ds, {"seqlen": len},
                     save_path=str(tmp_path)).run_map_reduce()
        sampler = DeepSpeedDataSampler(
            {"curriculum_learning": {
                "enabled": True,
                "curriculum_metrics": {
                    "seqlen": {
                        "sample_to_metric_path": str(tmp_path),
                        "difficulty_type": "value",
                        "min_difficulty": 8, "max_difficulty": 32,
                        "schedule_type": "fixed_linear",
                        "schedule_config": {"total_curriculum_step": 4,
                                            "difficulty_step": 8},
                    }}}},
            one_epoch_total_samples=len(ds), micro_batch_size=2,
            data_parallel_rank=0, data_parallel_size=1)
        batch = sampler.get_next_global_batch()
        # early curriculum: only short sequences eligible
        assert all(len(ds[i]) <= 8 for i in batch), \
            [len(ds[i]) for i in batch]


class TestMegatronIndexedDataset:
    """Interop with the reference's Megatron-LM mmap layout
    (deepspeed/runtime/data_pipeline/data_sampling/indexed_dataset.py:369
    MMapIndexedDataset.Index, magic MMIDIDX): existing corpora are read
    without re-encoding."""

    @staticmethod
    def _write_reference_layout(prefix, seqs, doc_idx, dtype=np.int32):
        """Handwritten writer following the REFERENCE's byte layout (so the
        test does not trust our own builder): magic + u64 version + u8
        dtype code + u64 n + u64 docs + i32 sizes + i64 byte pointers +
        i64 doc_idx; .bin = concatenated arrays."""
        import struct

        dtype = np.dtype(dtype)
        code = {np.dtype(np.int32): 4, np.dtype(np.uint16): 8}[dtype]
        with open(prefix + ".bin", "wb") as f:
            for s in seqs:
                f.write(np.asarray(s, dtype).tobytes())
        sizes = np.asarray([len(s) for s in seqs], np.int32)
        pointers = np.zeros(len(seqs), np.int64)
        np.cumsum(sizes[:-1].astype(np.int64) * dtype.itemsize,
                  out=pointers[1:])
        with open(prefix + ".idx", "wb") as f:
            f.write(b"MMIDIDX\x00\x00")
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<B", code))
            f.write(struct.pack("<Q", len(seqs)))
            f.write(struct.pack("<Q", len(doc_idx)))
            f.write(sizes.tobytes())
            f.write(pointers.tobytes())
            f.write(np.asarray(doc_idx, np.int64).tobytes())

    def test_reads_reference_layout(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_sampling.indexed_dataset import (
            MegatronMMapIndexedDataset,
            load_indexed_dataset,
        )

        prefix = str(tmp_path / "corpus")
        rng = np.random.default_rng(0)
        seqs = [rng.integers(0, 50000, rng.integers(3, 40)).astype(np.int32)
                for _ in range(17)]
        doc_idx = [0, 5, 11, 17]
        self._write_reference_layout(prefix, seqs, doc_idx)

        ds = MegatronMMapIndexedDataset(prefix)
        assert len(ds) == 17
        assert ds.dtype == np.int32
        for i, s in enumerate(seqs):
            np.testing.assert_array_equal(ds[i], s)
        np.testing.assert_array_equal(ds.sizes, [len(s) for s in seqs])
        np.testing.assert_array_equal(ds.doc_idx, doc_idx)
        # windowed access
        np.testing.assert_array_equal(ds.get(3, offset=2, length=4),
                                      seqs[3][2:6])
        # magic sniffing dispatches to the Megatron reader
        auto = load_indexed_dataset(prefix)
        assert isinstance(auto, MegatronMMapIndexedDataset)

    def test_builder_roundtrip_and_autodetect(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_sampling.indexed_dataset import (
            MegatronMMapIndexedDataset,
            MegatronMMapIndexedDatasetBuilder,
            MMapIndexedDataset,
            MMapIndexedDatasetBuilder,
            load_indexed_dataset,
        )

        rng = np.random.default_rng(1)
        seqs = [rng.integers(0, 60000, 9).astype(np.uint16)
                for _ in range(6)]

        mprefix = str(tmp_path / "meg")
        b = MegatronMMapIndexedDatasetBuilder(mprefix, dtype=np.uint16)
        for i, s in enumerate(seqs):
            b.add_item(s)
            if i in (2, 4):
                b.end_document()
        b.finalize()
        ds = MegatronMMapIndexedDataset(mprefix)
        for i, s in enumerate(seqs):
            np.testing.assert_array_equal(ds[i], s)
        np.testing.assert_array_equal(ds.doc_idx, [0, 3, 5, 6])

        # byte-level: our builder's index must parse as the handwritten
        # reference layout does (same header fields)
        raw = open(mprefix + ".idx", "rb").read()
        assert raw[:9] == b"MMIDIDX\x00\x00"

        nprefix = str(tmp_path / "native")
        nb = MMapIndexedDatasetBuilder(nprefix, dtype=np.uint16)
        for s in seqs:
            nb.add_item(s)
        nb.finalize()
        assert isinstance(load_indexed_dataset(nprefix), MMapIndexedDataset)
        assert MegatronMMapIndexedDataset.exists(mprefix)
        assert not MegatronMMapIndexedDataset.exists(nprefix)
