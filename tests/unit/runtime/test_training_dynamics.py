"""PLD / eigenvalue / MoQ quantizer / sparse tensor — analogs of reference
tests for runtime training-dynamics features."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


class TestProgressiveLayerDrop:
    def test_theta_schedule(self):
        from deepspeed_tpu.runtime.progressive_layer_drop import (
            ProgressiveLayerDrop,
        )

        pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
        assert pld.get_theta() == 1.0
        pld.update_state(0)
        assert np.isclose(pld.get_theta(), 1.0)
        pld.update_state(1000)
        assert 0.5 <= pld.get_theta() < 0.55
        state = pld.get_state()
        assert state["progressive_layer_drop"] is True

    def test_keep_prob_depth_gradient(self):
        from deepspeed_tpu.runtime.progressive_layer_drop import pld_keep_prob

        probs = [pld_keep_prob(0.5, i, 10) for i in range(10)]
        assert probs[0] > probs[-1]
        assert np.isclose(probs[-1], 0.5)

    def test_maybe_drop_layer(self):
        from deepspeed_tpu.runtime.progressive_layer_drop import (
            maybe_drop_layer,
        )

        x = jnp.ones((4,))
        fn = lambda h: h * 2
        # deterministic → always runs
        out = maybe_drop_layer(jax.random.PRNGKey(0), 0.1, x, fn,
                               deterministic=True)
        np.testing.assert_array_equal(np.asarray(out), 2.0)
        # keep_prob 0 → identity (layer skipped)
        out = maybe_drop_layer(jax.random.PRNGKey(0), 0.0, x, fn)
        np.testing.assert_array_equal(np.asarray(out), 1.0)


class TestEigenvalue:
    def test_quadratic_eigenvalue(self):
        """loss = 0.5 * x^T diag(d) x → max Hessian eigenvalue = max(d)."""
        from deepspeed_tpu.runtime.eigenvalue import Eigenvalue

        d = jnp.asarray([1.0, 5.0, 3.0])
        params = {"w": jnp.asarray([0.3, -0.2, 0.9])}

        def loss(p):
            return 0.5 * jnp.sum(d * p["w"] ** 2)

        ev = Eigenvalue(max_iter=200, tol=1e-4, stability=0.0, layer_num=1)
        results = ev.compute_eigenvalue(loss, params,
                                        rng=jax.random.PRNGKey(0))
        value, layer_id = results[0]
        assert np.isclose(value, 5.0, rtol=1e-2), value

    def test_block_selection(self):
        from deepspeed_tpu.runtime.eigenvalue import Eigenvalue

        params = {"encoder": {"layer": {"0": {"w": jnp.ones((2, 2))},
                                        "1": {"w": jnp.ones((2, 2))}}}}
        ev = Eigenvalue(layer_name="encoder.layer", layer_num=2, max_iter=5)
        assert ev.select_block(params, 0) is not None
        assert ev.select_block(params, 1) is not None


class TestQuantizer:
    def test_highbit_symmetric_preserves_range(self):
        from deepspeed_tpu.runtime.quantize import quantize_highbit

        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((8, 16)).astype(np.float32))
        q = quantize_highbit(x, num_bits=8, q_groups=4)
        assert q.shape == x.shape
        assert float(jnp.max(jnp.abs(q - x))) < 0.05  # 8-bit is close

    def test_lower_bits_more_error(self):
        from deepspeed_tpu.runtime.quantize import quantize_highbit

        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((8, 16)).astype(np.float32))
        e8 = float(jnp.mean((quantize_highbit(x, 8) - x) ** 2))
        e4 = float(jnp.mean((quantize_highbit(x, 4) - x) ** 2))
        e2 = float(jnp.mean((quantize_highbit(x, 2) - x) ** 2))
        assert e8 < e4 < e2

    def test_ternary_binary(self):
        from deepspeed_tpu.runtime.quantize import (
            quantize_binary,
            quantize_ternary,
        )

        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((4, 8)).astype(np.float32))
        t = np.asarray(quantize_ternary(x))
        assert len(np.unique(np.round(np.abs(t), 5))) <= 2  # {0, alpha}
        b = np.asarray(quantize_binary(x))
        assert (np.abs(b) > 0).all()

    def test_progressive_bit_reduction(self):
        from deepspeed_tpu.runtime.quantize import Quantizer

        q = Quantizer(q_groups=2, layer_num=1, q_verbose=False)
        q.quantize_settings(start_bits=16, target_bits=4, period=5)
        params = {"w": jnp.asarray(np.random.default_rng(1)
                                   .standard_normal((4, 8))
                                   .astype(np.float32))}
        for _ in range(30):
            params = q.quantize(params)
        assert q.q_start_bits[0] == 4, q.q_start_bits
        # values now on a coarse grid
        u = np.unique(np.round(np.asarray(params["w"]), 6))
        assert len(u) <= 2 ** 4 * 2 + 1, len(u)

    def test_overflow_skips(self):
        from deepspeed_tpu.runtime.quantize import Quantizer

        q = Quantizer()
        params = {"w": jnp.ones((2, 2))}
        out = q.quantize(params, overflow=True)
        assert out is params
        assert q.qsteps == 0


class TestSparseTensor:
    def test_roundtrip(self):
        from deepspeed_tpu.runtime.sparse_tensor import SparseTensor

        dense = jnp.zeros((6, 4)).at[jnp.asarray([1, 4])].set(
            jnp.ones((2, 4)))
        st = SparseTensor(dense)
        assert st.dims == (6, 4)
        np.testing.assert_array_equal(np.asarray(st.indices), [1, 4])
        np.testing.assert_array_equal(np.asarray(st.to_dense()),
                                      np.asarray(dense))
        nnz, total = st.sparse_size()
        assert nnz < total

    def test_add(self):
        from deepspeed_tpu.runtime.sparse_tensor import SparseTensor

        a = SparseTensor(jnp.zeros((4, 2)).at[0].set(1.0))
        b = SparseTensor(jnp.zeros((4, 2)).at[2].set(2.0))
        c = a.add(b)
        dense = np.asarray(c.to_dense())
        assert dense[0, 0] == 1.0 and dense[2, 0] == 2.0


class TestWeightQuantization:
    def test_roundtrip_error_bounded(self):
        from deepspeed_tpu.runtime.weight_quantizer import WeightQuantization

        rng = np.random.default_rng(0)
        sd = {"mlp.weight": rng.standard_normal((32, 32)).astype(np.float32),
              "ln.weight": np.ones(32, np.float32)}
        wq = WeightQuantization(quantize_groups=4, mlp_extra_grouping=True)
        qsd, scales = wq.quantize_state_dict(sd)
        assert qsd["mlp.weight"].dtype == np.int8
        assert "ln.weight" not in scales  # 1-D untouched
        deq = WeightQuantization.dequantize_state_dict(qsd, scales)
        err = np.abs(deq["mlp.weight"] - sd["mlp.weight"]).max()
        assert err < np.abs(sd["mlp.weight"]).max() / 100, err

    def test_int8_shrinks_storage(self):
        from deepspeed_tpu.runtime.weight_quantizer import WeightQuantization

        sd = {"w": np.ones((64, 64), np.float32)}
        qsd, scales = WeightQuantization().quantize_state_dict(sd)
        assert qsd["w"].nbytes * 4 == sd["w"].nbytes
