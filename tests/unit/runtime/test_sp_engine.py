"""Engine-level sequence parallelism: GPT-2 training with the `seq` mesh axis.

Verifies the full composition — batch dim sharded over `data`, sequence dim
sharded over `seq`, ring/Ulysses attention inside the compiled train step,
ZeRO state sharded over (data, expert, seq) — produces the same losses as the
plain data-parallel run (same seed, same batches).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel import initialize_mesh, reset_mesh


def _train_losses(mesh_kwargs, model_cfg_kwargs, steps=3, zero_stage=2):
    reset_mesh()
    mesh = initialize_mesh(**mesh_kwargs)
    cfg = GPT2Config(vocab_size=128, n_positions=32, n_embd=32, n_layer=2,
                     n_head=4, dtype=jnp.float32, **model_cfg_kwargs)
    engine, _, _, _ = ds.initialize(
        model=GPT2LMHeadModel(cfg),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "zero_optimization": {"stage": zero_stage},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        },
        mesh=mesh)
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(steps):
        batch = {"input_ids": rng.integers(
            0, 128, (engine.train_batch_size(), 32)).astype(np.int32)}
        losses.append(float(engine.train_batch(batch=batch)))
    return losses


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_sp_training_matches_dp(strategy):
    # dp=2 × sp=4: batch of 2 samples' sequences split 4 ways
    sp_losses = _train_losses({"data": 2, "seq": 4},
                              {"sequence_parallel": strategy})
    # same batch world (dp=2) without sequence parallelism; tp=4 absorbs the
    # remaining devices and is mathematically identical
    dp_losses = _train_losses({"data": 2, "model": 4}, {})
    np.testing.assert_allclose(sp_losses, dp_losses, rtol=2e-4, atol=2e-4)
    assert all(np.isfinite(sp_losses))


def test_sp_with_tp_composes():
    # dp=2 × sp=2 × tp=2
    losses = _train_losses({"data": 2, "seq": 2, "model": 2},
                           {"sequence_parallel": "ring"}, zero_stage=3)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 1.5  # sanity: not diverging wildly
