"""Smoke tests for the bench tooling: ``check_regression.py`` exit
codes and the ``bench.py`` entry-point wiring (no model is built — the
serving rows are exercised end-to-end by tests/unit/serving/)."""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
CHECK = REPO / "check_regression.py"


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def _run(*argv):
    return subprocess.run([sys.executable, str(CHECK), *argv],
                          capture_output=True, text=True)


class TestCheckRegression:
    def test_within_threshold_passes(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json", {"value": 95.0})
        r = _run(base, cand)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "ok" in r.stdout

    def test_regression_fails(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json", {"value": 80.0})
        r = _run(base, cand)
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout

    def test_improvement_passes(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json", {"value": 150.0})
        assert _run(base, cand).returncode == 0

    def test_lower_is_better_direction(self, tmp_path):
        # latency-style metric: candidate 30% slower must fail, 30%
        # faster must pass
        base = _write(tmp_path, "base.json",
                      {"detail": {"stall_free": {"step_gap_p99_ms": 10.0}}})
        worse = _write(tmp_path, "worse.json",
                       {"detail": {"stall_free": {"step_gap_p99_ms": 13.0}}})
        better = _write(tmp_path, "better.json",
                        {"detail": {"stall_free": {"step_gap_p99_ms": 7.0}}})
        m = "detail.stall_free.step_gap_p99_ms:lower"
        assert _run(base, worse, "--metric", m).returncode == 1
        assert _run(base, better, "--metric", m).returncode == 0

    def test_custom_threshold(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json", {"value": 95.0})
        assert _run(base, cand, "--threshold", "0.02").returncode == 1
        assert _run(base, cand, "--threshold", "0.10").returncode == 0

    def test_multiple_metrics_any_failure_fails(self, tmp_path):
        base = _write(tmp_path, "base.json",
                      {"value": 100.0, "detail": {"req_s": 50.0}})
        cand = _write(tmp_path, "cand.json",
                      {"value": 100.0, "detail": {"req_s": 20.0}})
        r = _run(base, cand, "--metric", "value",
                 "--metric", "detail.req_s:higher")
        assert r.returncode == 1

    def test_missing_metric_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 1.0})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        r = _run(base, cand, "--metric", "detail.nope")
        assert r.returncode == 2
        assert "not found" in r.stderr

    def test_missing_file_exits_2(self, tmp_path):
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        assert _run(str(tmp_path / "absent.json"), cand).returncode == 2

    def test_bad_json_exits_2(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        assert _run(str(bad), cand).returncode == 2

    def test_non_numeric_metric_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": "fast"})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        assert _run(base, cand).returncode == 2

    def test_bad_direction_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 1.0})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        assert _run(base, cand, "--metric", "value:sideways").returncode == 2

    def test_max_recompiles_within_cap_passes(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json",
                      {"value": 100.0,
                       "detail": {"recompiles_after_warmup": 0}})
        r = _run(base, cand, "--max-recompiles", "0")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "recompiles_after_warmup" in r.stdout

    def test_max_recompiles_over_cap_fails(self, tmp_path):
        # absolute gate: fails even when every relative metric improves
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json",
                      {"value": 200.0,
                       "detail": {"recompiles_after_warmup": 3}})
        r = _run(base, cand, "--max-recompiles", "2")
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout

    def test_max_recompiles_missing_field_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 1.0})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        r = _run(base, cand, "--max-recompiles", "0")
        assert r.returncode == 2
        assert "recompiles_after_warmup" in r.stderr

    @staticmethod
    def _chaos(value=1.0, leaks=0, inv=True, tl=True):
        return {"value": value,
                "detail": {"slot_leaks": leaks, "invariants_ok": inv,
                           "timelines_complete": tl}}

    def test_zero_leaks_clean_passes(self, tmp_path):
        base = _write(tmp_path, "base.json", self._chaos())
        cand = _write(tmp_path, "cand.json", self._chaos())
        r = _run(base, cand, "--require-zero-leaks")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "slot_leaks" in r.stdout

    def test_zero_leaks_leaked_slot_fails(self, tmp_path):
        # absolute gate: one leaked slot fails even with value improved
        base = _write(tmp_path, "base.json", self._chaos(value=1.0))
        cand = _write(tmp_path, "cand.json", self._chaos(value=2.0, leaks=1))
        r = _run(base, cand, "--require-zero-leaks")
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout

    def test_zero_leaks_invariant_failure_fails(self, tmp_path):
        base = _write(tmp_path, "base.json", self._chaos())
        cand = _write(tmp_path, "cand.json", self._chaos(inv=False))
        assert _run(base, cand, "--require-zero-leaks").returncode == 1

    def test_zero_leaks_open_timeline_fails(self, tmp_path):
        base = _write(tmp_path, "base.json", self._chaos())
        cand = _write(tmp_path, "cand.json", self._chaos(tl=False))
        assert _run(base, cand, "--require-zero-leaks").returncode == 1

    def test_zero_leaks_non_boolean_exits_2(self, tmp_path):
        # "true"-the-string must not pass as true-the-boolean
        base = _write(tmp_path, "base.json", self._chaos())
        cand = _write(tmp_path, "cand.json", self._chaos(inv="true"))
        r = _run(base, cand, "--require-zero-leaks")
        assert r.returncode == 2
        assert "invariants_ok" in r.stderr

    def test_zero_leaks_missing_field_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", self._chaos())
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        assert _run(base, cand, "--require-zero-leaks").returncode == 2


class TestBenchEntryPoints:
    def test_serving_stall_entry_wired(self):
        # arg parsing only: the row itself runs in the serving tests'
        # environment; here we just pin the CLI contract
        src = (REPO / "bench.py").read_text()
        assert "serving-stall" in src
        assert "def serving_stall_main" in src
        assert "--trace" in src
        assert "recompiles_after_warmup" in src

    def test_serving_chaos_entry_wired(self):
        # the chaos row must exist, must be dispatched BEFORE the plain
        # "serving" check (exact-element matching would otherwise never
        # reach it), and must emit every invariant --require-zero-leaks
        # gates on
        src = (REPO / "bench.py").read_text()
        assert "def serving_chaos_main" in src
        assert src.index('"serving-chaos" in argv') \
            < src.index('"serving-stall" in argv')
        for key in ("slot_leaks", "invariants_ok", "timelines_complete",
                    "goodput"):
            assert key in src

    def test_check_regression_importable(self):
        # the module must import without side effects (argparse only
        # runs under __main__) so the driver can vendor it
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "check_regression", CHECK)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert callable(mod.main)
