"""Smoke tests for the bench tooling: ``check_regression.py`` exit
codes and the ``bench.py`` entry-point wiring (no model is built — the
serving rows are exercised end-to-end by tests/unit/serving/)."""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
CHECK = REPO / "check_regression.py"


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def _run(*argv):
    return subprocess.run([sys.executable, str(CHECK), *argv],
                          capture_output=True, text=True)


class TestCheckRegression:
    def test_within_threshold_passes(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json", {"value": 95.0})
        r = _run(base, cand)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "ok" in r.stdout

    def test_regression_fails(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json", {"value": 80.0})
        r = _run(base, cand)
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout

    def test_improvement_passes(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json", {"value": 150.0})
        assert _run(base, cand).returncode == 0

    def test_lower_is_better_direction(self, tmp_path):
        # latency-style metric: candidate 30% slower must fail, 30%
        # faster must pass
        base = _write(tmp_path, "base.json",
                      {"detail": {"stall_free": {"step_gap_p99_ms": 10.0}}})
        worse = _write(tmp_path, "worse.json",
                       {"detail": {"stall_free": {"step_gap_p99_ms": 13.0}}})
        better = _write(tmp_path, "better.json",
                        {"detail": {"stall_free": {"step_gap_p99_ms": 7.0}}})
        m = "detail.stall_free.step_gap_p99_ms:lower"
        assert _run(base, worse, "--metric", m).returncode == 1
        assert _run(base, better, "--metric", m).returncode == 0

    def test_custom_threshold(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json", {"value": 95.0})
        assert _run(base, cand, "--threshold", "0.02").returncode == 1
        assert _run(base, cand, "--threshold", "0.10").returncode == 0

    def test_multiple_metrics_any_failure_fails(self, tmp_path):
        base = _write(tmp_path, "base.json",
                      {"value": 100.0, "detail": {"req_s": 50.0}})
        cand = _write(tmp_path, "cand.json",
                      {"value": 100.0, "detail": {"req_s": 20.0}})
        r = _run(base, cand, "--metric", "value",
                 "--metric", "detail.req_s:higher")
        assert r.returncode == 1

    def test_missing_metric_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 1.0})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        r = _run(base, cand, "--metric", "detail.nope")
        assert r.returncode == 2
        assert "not found" in r.stderr

    def test_missing_file_exits_2(self, tmp_path):
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        assert _run(str(tmp_path / "absent.json"), cand).returncode == 2

    def test_bad_json_exits_2(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        assert _run(str(bad), cand).returncode == 2

    def test_non_numeric_metric_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": "fast"})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        assert _run(base, cand).returncode == 2

    def test_bad_direction_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 1.0})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        assert _run(base, cand, "--metric", "value:sideways").returncode == 2

    def test_max_recompiles_within_cap_passes(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json",
                      {"value": 100.0,
                       "detail": {"recompiles_after_warmup": 0}})
        r = _run(base, cand, "--max-recompiles", "0")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "recompiles_after_warmup" in r.stdout

    def test_max_recompiles_over_cap_fails(self, tmp_path):
        # absolute gate: fails even when every relative metric improves
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json",
                      {"value": 200.0,
                       "detail": {"recompiles_after_warmup": 3}})
        r = _run(base, cand, "--max-recompiles", "2")
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout

    def test_max_recompiles_missing_field_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 1.0})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        r = _run(base, cand, "--max-recompiles", "0")
        assert r.returncode == 2
        assert "recompiles_after_warmup" in r.stderr

    @staticmethod
    def _lint(errors=0):
        # shape of a `bin/graftlint --json` report
        return {"version": 1,
                "summary": {"files": 25, "total": errors, "errors": errors,
                            "warnings": 0, "suppressed": 4, "baselined": 0},
                "findings": []}

    def test_max_lint_errors_within_cap_passes(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json", {"value": 100.0})
        lint = _write(tmp_path, "lint.json", self._lint(errors=0))
        r = _run(base, cand, "--lint-json", lint, "--max-lint-errors", "0")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "graftlint" in r.stdout

    def test_max_lint_errors_over_cap_fails(self, tmp_path):
        # absolute gate: static debt fails even when metrics improve
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json", {"value": 200.0})
        lint = _write(tmp_path, "lint.json", self._lint(errors=3))
        r = _run(base, cand, "--lint-json", lint, "--max-lint-errors", "2")
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout

    def test_max_lint_errors_without_lint_json_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 1.0})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        r = _run(base, cand, "--max-lint-errors", "0")
        assert r.returncode == 2
        assert "--lint-json" in r.stderr

    def test_max_lint_errors_malformed_report_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 1.0})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        lint = _write(tmp_path, "lint.json", {"summary": {}})
        r = _run(base, cand, "--lint-json", lint, "--max-lint-errors", "0")
        assert r.returncode == 2
        assert "summary.errors" in r.stderr

    def test_lint_json_repeatable_both_clean_passes(self, tmp_path):
        # one run gates the lint-tier and `--tier sync` reports together
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json", {"value": 100.0})
        lint = _write(tmp_path, "lint.json", self._lint(errors=0))
        sync = _write(tmp_path, "sync.json", self._lint(errors=0))
        r = _run(base, cand, "--lint-json", lint, "--lint-json", sync,
                 "--max-lint-errors", "0")
        assert r.returncode == 0, r.stdout + r.stderr
        assert r.stdout.count("graftlint") == 2
        assert "lint.json" in r.stdout and "sync.json" in r.stdout

    def test_lint_json_repeatable_any_dirty_fails(self, tmp_path):
        # the cap applies to each report independently
        base = _write(tmp_path, "base.json", {"value": 100.0})
        cand = _write(tmp_path, "cand.json", {"value": 200.0})
        lint = _write(tmp_path, "lint.json", self._lint(errors=0))
        sync = _write(tmp_path, "sync.json", self._lint(errors=1))
        r = _run(base, cand, "--lint-json", lint, "--lint-json", sync,
                 "--max-lint-errors", "0")
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout
        assert "sync.json" in r.stdout

    @staticmethod
    def _chaos(value=1.0, leaks=0, inv=True, tl=True):
        return {"value": value,
                "detail": {"slot_leaks": leaks, "invariants_ok": inv,
                           "timelines_complete": tl}}

    def test_zero_leaks_clean_passes(self, tmp_path):
        base = _write(tmp_path, "base.json", self._chaos())
        cand = _write(tmp_path, "cand.json", self._chaos())
        r = _run(base, cand, "--require-zero-leaks")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "slot_leaks" in r.stdout

    def test_zero_leaks_leaked_slot_fails(self, tmp_path):
        # absolute gate: one leaked slot fails even with value improved
        base = _write(tmp_path, "base.json", self._chaos(value=1.0))
        cand = _write(tmp_path, "cand.json", self._chaos(value=2.0, leaks=1))
        r = _run(base, cand, "--require-zero-leaks")
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout

    def test_zero_leaks_invariant_failure_fails(self, tmp_path):
        base = _write(tmp_path, "base.json", self._chaos())
        cand = _write(tmp_path, "cand.json", self._chaos(inv=False))
        assert _run(base, cand, "--require-zero-leaks").returncode == 1

    def test_zero_leaks_open_timeline_fails(self, tmp_path):
        base = _write(tmp_path, "base.json", self._chaos())
        cand = _write(tmp_path, "cand.json", self._chaos(tl=False))
        assert _run(base, cand, "--require-zero-leaks").returncode == 1

    def test_zero_leaks_non_boolean_exits_2(self, tmp_path):
        # "true"-the-string must not pass as true-the-boolean
        base = _write(tmp_path, "base.json", self._chaos())
        cand = _write(tmp_path, "cand.json", self._chaos(inv="true"))
        r = _run(base, cand, "--require-zero-leaks")
        assert r.returncode == 2
        assert "invariants_ok" in r.stderr

    def test_zero_leaks_missing_field_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", self._chaos())
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        assert _run(base, cand, "--require-zero-leaks").returncode == 2


class TestJourneyGate:
    @staticmethod
    def _disagg(value=1.0, finished=6, complete=6, overhead=0.5):
        return {"value": value, "detail": {
            "journeys": {"total": finished, "finished": finished,
                         "complete": complete, "incomplete": []},
            "efficiency": {"goodput_slo": 1.0,
                           "overhead_pct": overhead}}}

    def test_complete_journeys_pass(self, tmp_path):
        base = _write(tmp_path, "base.json", self._disagg())
        cand = _write(tmp_path, "cand.json", self._disagg())
        r = _run(base, cand, "--require-complete-journeys")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "journeys" in r.stdout

    def test_incomplete_journey_fails_even_with_value_improved(
            self, tmp_path):
        # absolute gate: one journey that finished but does not stitch
        # (an open or parked home) fails regardless of the headline
        base = _write(tmp_path, "base.json", self._disagg(value=1.0))
        cand = _write(tmp_path, "cand.json",
                      self._disagg(value=2.0, complete=5))
        r = _run(base, cand, "--require-complete-journeys")
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout

    def test_missing_journeys_block_exits_2(self, tmp_path):
        # a bench that silently stopped emitting detail.journeys is a
        # broken invocation, not a pass
        base = _write(tmp_path, "base.json", self._disagg())
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        r = _run(base, cand, "--require-complete-journeys")
        assert r.returncode == 2
        assert "journeys" in r.stderr

    def test_malformed_journeys_detail_exits_2(self, tmp_path):
        # "6"-the-string (or a bool) must not compare as a count
        base = _write(tmp_path, "base.json", self._disagg())
        bad = self._disagg()
        bad["detail"]["journeys"]["complete"] = "6"
        cand = _write(tmp_path, "cand.json", bad)
        assert _run(base, cand,
                    "--require-complete-journeys").returncode == 2

    def test_disagg_gate_combination(self, tmp_path):
        # the serving-disagg driver invocation stacks the overhead cap,
        # the journey gate and the recompile cap
        def row(complete=6, overhead=0.5, recompiles=0):
            d = self._disagg(complete=complete, overhead=overhead)
            d["detail"]["recompiles_after_warmup"] = recompiles
            return d

        gates = ("--max-overhead-pct", "3",
                 "--require-complete-journeys", "--max-recompiles", "0")
        base = _write(tmp_path, "base.json", row())
        assert _run(base, _write(tmp_path, "ok.json", row()),
                    *gates).returncode == 0
        assert _run(base, _write(tmp_path, "j.json", row(complete=4)),
                    *gates).returncode == 1
        assert _run(base, _write(tmp_path, "o.json", row(overhead=7.5)),
                    *gates).returncode == 1
        assert _run(base, _write(tmp_path, "r.json", row(recompiles=1)),
                    *gates).returncode == 1


class TestEfficiencyGates:
    @staticmethod
    def _eff(goodput=1.0, overhead=1.0, mfu=0.3):
        return {"value": 1.0,
                "detail": {"efficiency": {"goodput_slo": goodput,
                                          "overhead_pct": overhead,
                                          "mfu": mfu}}}

    def test_min_goodput_passes_and_fails(self, tmp_path):
        base = _write(tmp_path, "base.json", self._eff())
        good = _write(tmp_path, "good.json", self._eff(goodput=0.97))
        bad = _write(tmp_path, "bad.json", self._eff(goodput=0.80))
        r = _run(base, good, "--min-goodput", "0.9")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "goodput_slo" in r.stdout
        r = _run(base, bad, "--min-goodput", "0.9")
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout

    def test_min_goodput_is_absolute(self, tmp_path):
        # candidate better than baseline still fails below the floor
        base = _write(tmp_path, "base.json", self._eff(goodput=0.5))
        cand = _write(tmp_path, "cand.json", self._eff(goodput=0.7))
        assert _run(base, cand, "--min-goodput", "0.9").returncode == 1

    def test_max_overhead_pct(self, tmp_path):
        base = _write(tmp_path, "base.json", self._eff())
        lean = _write(tmp_path, "lean.json", self._eff(overhead=1.2))
        fat = _write(tmp_path, "fat.json", self._eff(overhead=7.5))
        assert _run(base, lean, "--max-overhead-pct", "3").returncode == 0
        r = _run(base, fat, "--max-overhead-pct", "3")
        assert r.returncode == 1
        assert "overhead_pct" in r.stdout

    def test_missing_efficiency_block_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", self._eff())
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        assert _run(base, cand, "--min-goodput", "0.9").returncode == 2

    def test_warn_metric_never_fails(self, tmp_path):
        # a 50% mfu drop on a CPU box: prints WARNING, exits 0
        base = _write(tmp_path, "base.json", self._eff(mfu=0.4))
        cand = _write(tmp_path, "cand.json", self._eff(mfu=0.2))
        r = _run(base, cand, "--warn-metric", "detail.efficiency.mfu")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "WARNING" in r.stdout
        # within threshold: plain ok, no warning
        steady = _write(tmp_path, "steady.json", self._eff(mfu=0.39))
        r = _run(base, steady, "--warn-metric", "detail.efficiency.mfu")
        assert r.returncode == 0
        assert "WARNING" not in r.stdout

    def test_warn_metric_missing_field_still_exits_2(self, tmp_path):
        # warn-only softens the verdict, not the plumbing: a typo'd
        # path must stay loud
        base = _write(tmp_path, "base.json", self._eff())
        cand = _write(tmp_path, "cand.json", self._eff())
        assert _run(base, cand, "--warn-metric",
                    "detail.efficiency.mfuu").returncode == 2


class TestSignatureGate:
    """--signatures-json / --require-signature-match: the graftcheck
    absolute gate — static enumeration must equal the runtime warmup
    manifest byte-for-byte, in both directions."""

    @staticmethod
    def _static_doc():
        from deepspeed_tpu.analysis import (default_check_envs,
                                            enumerate_union)
        envs = default_check_envs()
        res = enumerate_union(envs, str(REPO))
        return {"version": 1, "configs": envs,
                "programs": {k: sorted(v)
                             for k, v in res.programs.items()}}

    def test_signature_match_passes(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 1.0})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        man = _write(tmp_path, "signatures.json", self._static_doc())
        r = _run(base, cand, "--signatures-json", man,
                 "--require-signature-match")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "signatures [graftcheck]" in r.stdout

    def test_signature_divergence_fails_both_directions(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 1.0})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        doc = self._static_doc()
        # runtime manifest MISSING a statically-reachable signature:
        # that shape was never warmed and will compile post-warmup
        lean = {**doc, "programs": {
            k: (v[:-1] if k == "InferenceEngine._jit_decode" else v)
            for k, v in doc["programs"].items()}}
        man = _write(tmp_path, "lean.json", lean)
        r = _run(base, cand, "--signatures-json", man,
                 "--require-signature-match")
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout
        # runtime manifest with a signature the static set MISSED:
        # the checker lost coverage
        fat = {**doc, "programs": dict(
            doc["programs"],
            **{"InferenceEngine._jit_decode":
               doc["programs"]["InferenceEngine._jit_decode"]
               + ["(int32[99,99])"]})}
        man2 = _write(tmp_path, "fat.json", fat)
        r2 = _run(base, cand, "--signatures-json", man2,
                  "--require-signature-match")
        assert r2.returncode == 1
        assert "(int32[99,99])" in r2.stdout

    def test_gate_flag_without_manifest_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 1.0})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        r = _run(base, cand, "--require-signature-match")
        assert r.returncode == 2
        assert "--signatures-json" in r.stderr

    def test_malformed_manifest_exits_2(self, tmp_path):
        base = _write(tmp_path, "base.json", {"value": 1.0})
        cand = _write(tmp_path, "cand.json", {"value": 1.0})
        man = _write(tmp_path, "notman.json", {"hello": 1})
        r = _run(base, cand, "--signatures-json", man,
                 "--require-signature-match")
        assert r.returncode == 2
        assert "signatures.json" in r.stderr

    def test_bench_signatures_flag_wired(self):
        src = (REPO / "bench.py").read_text()
        assert "--signatures" in src
        assert "_SIGNATURES_PATH" in src
        assert src.count("export_signatures") >= 4  # both rows, both arms


class TestBenchEntryPoints:
    def test_serving_stall_entry_wired(self):
        # arg parsing only: the row itself runs in the serving tests'
        # environment; here we just pin the CLI contract
        src = (REPO / "bench.py").read_text()
        assert "serving-stall" in src
        assert "def serving_stall_main" in src
        assert "--trace" in src
        assert "recompiles_after_warmup" in src

    def test_serving_chaos_entry_wired(self):
        # the chaos row must exist, must be dispatched BEFORE the plain
        # "serving" check (exact-element matching would otherwise never
        # reach it), and must emit every invariant --require-zero-leaks
        # gates on
        src = (REPO / "bench.py").read_text()
        assert "def serving_chaos_main" in src
        assert src.index('"serving-chaos" in argv') \
            < src.index('"serving-stall" in argv')
        for key in ("slot_leaks", "invariants_ok", "timelines_complete",
                    "goodput"):
            assert key in src
        # the flight-recorder drill: --dump-dir plumbing and the
        # exactly-one-post-mortem report the driver gates on
        for key in ("--dump-dir", "state_corruption", "post_mortem",
                    "exactly_one"):
            assert key in src

    def test_serving_async_entry_wired(self):
        # the async front-end row: dispatched BEFORE the plain
        # "serving" membership check, and emits the exact fields its
        # three-gate invocation (--min-goodput --require-zero-leaks
        # --max-recompiles 0) reads
        src = (REPO / "bench.py").read_text()
        assert "def serving_async_main" in src
        assert src.index('"serving-async" in argv') \
            < src.index('"serving" in argv')
        for key in ("ServingFrontend", "class_alerts",
                    "batch_actively_shed", "per_class_http"):
            assert key in src

    def test_serving_async_gate_combination(self, tmp_path):
        # the row's driver invocation stacks all three absolute gates;
        # a synthetic row in the serving-async shape must pass them
        # together, and each defect must fail alone
        def row(goodput=1.0, leaks=0, tl=True, recompiles=0):
            return {"value": goodput, "detail": {
                "slot_leaks": leaks, "invariants_ok": True,
                "timelines_complete": tl,
                "recompiles_after_warmup": recompiles,
                "efficiency": {"goodput_slo": goodput},
                "batch_actively_shed": True}}

        gates = ("--min-goodput", "0.95", "--require-zero-leaks",
                 "--max-recompiles", "0")
        base = _write(tmp_path, "base.json", row())
        r = _run(base, _write(tmp_path, "ok.json", row()), *gates)
        assert r.returncode == 0, r.stdout + r.stderr
        # top-class goodput below the floor (shedding ate the wrong
        # tier), a leaked slot, an open timeline, a recompile: each
        # alone must fail
        assert _run(base, _write(tmp_path, "gp.json", row(goodput=0.5)),
                    *gates).returncode == 1
        assert _run(base, _write(tmp_path, "lk.json", row(leaks=1)),
                    *gates).returncode == 1
        assert _run(base, _write(tmp_path, "tl.json", row(tl=False)),
                    *gates).returncode == 1
        assert _run(base, _write(tmp_path, "rc.json", row(recompiles=2)),
                    *gates).returncode == 1

    def test_serving_disagg_fleet_detail_wired(self):
        # the disagg row must emit every field its fleet-observability
        # gate invocation (--max-overhead-pct 3
        # --require-complete-journeys --max-recompiles 0) reads
        src = (REPO / "bench.py").read_text()
        assert "def serving_disagg_main" in src
        for key in ("journey_summary", "transfer_latency_p99_ms",
                    "efficiency_snapshot", "overhead_pct",
                    "require-complete-journeys",
                    "reset_efficiency_window"):
            assert key in src, key

    def test_check_regression_importable(self):
        # the module must import without side effects (argparse only
        # runs under __main__) so the driver can vendor it
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "check_regression", CHECK)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert callable(mod.main)
