"""Engine-level orbax (sharded/multi-host-path) checkpointing. In tests
the world is one process, so the orbax path is exercised directly via
the engine's split/restore helpers against sharded ZeRO-3 state."""

import os

import numpy as np
import pytest

import deepspeed_tpu as ds


def _engine(lr=1e-2):
    from deepspeed_tpu.parallel import mesh as mesh_mod
    from tests.unit.simple_model import SimpleModel

    mesh_mod.reset_mesh()
    config = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "Adam", "params": {"lr": lr}},
        "zero_optimization": {"stage": 3},
        "bf16": {"enabled": True},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                    config=config)
    return engine


def test_orbax_roundtrip_sharded_state(tmp_path):
    import jax

    from deepspeed_tpu.runtime.checkpoint_engine.orbax_checkpoint_engine import (
        OrbaxCheckpointEngine,
    )
    from tests.unit.simple_model import random_batch

    engine = _engine()
    b = random_batch(engine.train_batch_size())
    for _ in range(3):
        engine.train_batch(batch=b)

    # save via the orbax split (the multi-host save path's payload)
    arrays, meta = engine._orbax_split_state()
    oe = OrbaxCheckpointEngine()
    path = str(tmp_path / "ck" / "orbax_state")
    oe.save({"arrays": arrays, "meta": meta}, path)
    oe.commit("t")

    l_ref = float(engine.train_batch(batch=b))

    engine2 = _engine()
    engine2.train_batch(batch=b)
    loaded_dir, _ = engine2._load_orbax_checkpoint(str(tmp_path), "ck")
    assert loaded_dir == str(tmp_path)
    assert engine2.global_steps == 3
    l2 = float(engine2.train_batch(batch=b))
    assert np.isclose(l_ref, l2, rtol=1e-2), (l_ref, l2)
    # restored arrays keep the ZeRO shardings (compute params here are under
    # the stage-3 persistence threshold and stay replicated; the fp32
    # master always shards)
    m = engine2.state["master"]["linear_0"]["kernel"]
    assert any(e is not None for e in m.sharding.spec), m.sharding


def test_orbax_tolerates_optional_entry_mismatch(tmp_path):
    """fp16 save (has loss-scale state) → bf16 load (no scale): optional
    entries missing from the target must not break the restore."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import mesh as mesh_mod
    from deepspeed_tpu.runtime.checkpoint_engine.orbax_checkpoint_engine import (
        OrbaxCheckpointEngine,
    )
    from tests.unit.simple_model import SimpleModel, random_batch

    mesh_mod.reset_mesh()
    fp16_cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
        "fp16": {"enabled": True},
        "steps_per_print": 1000,
    }
    eng, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                 config=fp16_cfg)
    b = random_batch(eng.train_batch_size())
    for _ in range(2):
        eng.train_batch(batch=b)
    arrays, meta = eng._orbax_split_state()
    assert "scale" in arrays
    oe = OrbaxCheckpointEngine()
    oe.save({"arrays": arrays, "meta": meta},
            str(tmp_path / "m" / "orbax_state"))
    oe.commit("m")

    mesh_mod.reset_mesh()
    bf16_cfg = dict(fp16_cfg)
    bf16_cfg.pop("fp16")
    bf16_cfg["bf16"] = {"enabled": True}
    eng2, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                  config=bf16_cfg)
    eng2.train_batch(batch=b)
    eng2._load_orbax_checkpoint(str(tmp_path), "m")  # no crash
    assert eng2.global_steps == 2


def test_nebula_config_selects_async_engine(tmp_path):
    """nebula.enabled routes save_checkpoint through the async orbax
    engine end to end (reference NebulaCheckpointEngine selection)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import mesh as mesh_mod
    from deepspeed_tpu.runtime.checkpoint_engine.nebula_checkpoint_engine import (
        NebulaCheckpointEngine,
    )
    from tests.unit.simple_model import SimpleModel, random_batch

    mesh_mod.reset_mesh()
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
        "bf16": {"enabled": True},
        "nebula": {"enabled": True},
        "steps_per_print": 1000,
    }
    eng, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                 config=cfg)
    assert isinstance(eng.checkpoint_engine, NebulaCheckpointEngine)
    b = random_batch(eng.train_batch_size())
    for _ in range(2):
        eng.train_batch(batch=b)
    eng.save_checkpoint(str(tmp_path))
    l1 = float(eng.train_batch(batch=b))

    mesh_mod.reset_mesh()
    eng2, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                  config=dict(cfg))
    eng2.train_batch(batch=b)
    eng2.load_checkpoint(str(tmp_path))
    assert eng2.global_steps == 2
    l2 = float(eng2.train_batch(batch=b))
    import numpy as np

    assert np.isclose(l1, l2, rtol=1e-3), (l1, l2)
