"""Reshape maps + reference-checkpoint migration — analog of reference
``tests/unit/checkpoint/test_reshape_checkpoint.py``."""

import numpy as np
import pytest

from deepspeed_tpu.checkpoint import (
    DeepSpeedCheckpoint,
    get_model_3d_descriptor,
    model_3d_desc,
    reshape_meg_2d_parallel,
)


def test_reshape_222_to_111():
    m = reshape_meg_2d_parallel(2, 2, 1, 1)
    assert m.get_data(0, 0) == [0, 1, 2, 3]


def test_reshape_tp_shrink():
    m = reshape_meg_2d_parallel(1, 4, 1, 2)
    assert m.get_data(0, 0) == [0, 1]
    assert m.get_data(0, 1) == [2, 3]


def test_reshape_pp_shrink():
    m = reshape_meg_2d_parallel(4, 1, 2, 1)
    assert m.get_data(0, 0) == [0, 1]
    assert m.get_data(1, 0) == [2, 3]


def test_reshape_expansion_rejected():
    with pytest.raises(AssertionError):
        reshape_meg_2d_parallel(1, 2, 1, 4)


def test_3d_desc_reshape():
    src = model_3d_desc(pp_degree=2, tp_degree=2, dp_degree=2)
    tgt = model_3d_desc(pp_degree=1, tp_degree=1, dp_degree=1)
    ok, errs = src.can_reshape(tgt)
    assert ok, errs
    dp_maps = src.reshape(tgt)
    assert len(dp_maps) == 1
    # all 8 source ranks land on the single target coordinate
    assert sorted(dp_maps[0].get_data(0, 0)) == list(range(8))


def test_3d_desc_rejects_expansion():
    src = model_3d_desc(1, 1, 1)
    tgt = model_3d_desc(2, 1, 1)
    ok, errs = src.can_reshape(tgt)
    assert not ok and errs


def _make_reference_ckpt(tmp_path, tp=2, n_layers=2, hidden=8):
    """Fake Megatron-DeepSpeed layer-file checkpoint: layer_00 embedding,
    layer_01..n transformer, last = final norm; one file per tp rank."""
    torch = pytest.importorskip("torch")
    d = tmp_path / "ref_ckpt"
    d.mkdir()
    layer_ids = [0] + list(range(1, n_layers + 1)) + [n_layers + 1]
    for lid in layer_ids:
        for tp_rank in range(tp):
            if lid == 0:
                sd = {"word_embeddings.weight":
                      torch.randn(16 // tp, hidden)}
            elif lid == layer_ids[-1]:
                sd = {"weight": torch.ones(hidden), "bias": torch.zeros(hidden)}
            else:
                sd = {
                    "input_layernorm.weight": torch.ones(hidden),
                    "self_attention.query_key_value.weight":
                        torch.randn(3 * hidden // tp, hidden),
                    "self_attention.dense.weight":
                        torch.randn(hidden, hidden // tp),
                    "mlp.dense_h_to_4h.weight":
                        torch.randn(4 * hidden // tp, hidden),
                    "mlp.dense_4h_to_h.weight":
                        torch.randn(hidden, 4 * hidden // tp),
                }
            torch.save(sd, d / f"layer_{lid:02d}-model_{tp_rank:02d}"
                       f"-model_states.pt")
    for tp_rank in range(tp):
        torch.save({"iteration": 42},
                   d / f"mp_rank_{tp_rank:02d}_model_states.pt")
    return d


def test_3d_descriptor_from_reference_dir(tmp_path):
    d = _make_reference_ckpt(tmp_path, tp=2, n_layers=2)
    desc = get_model_3d_descriptor(str(d))
    assert desc.tp_degree == 2
    assert desc.pp_degree == 1


def test_deepspeed_checkpoint_reader(tmp_path):
    d = _make_reference_ckpt(tmp_path, tp=2, n_layers=2, hidden=8)
    ckpt = DeepSpeedCheckpoint(str(d))
    assert ckpt.original_tp_degree == 2
    assert ckpt.get_iteration() == 42
    # at the original tp, each tp index sees its own shard
    emb = ckpt.get_embedding_state(0)
    assert emb["word_embeddings.weight"].shape == (8, 8)
    t_states = ckpt.get_transformer_state(0, 0)
    assert t_states, "expected transformer layer states"

    # shrinking to tp=1 merges the shards
    ckpt1 = DeepSpeedCheckpoint(str(d), tp_degree=1)
    emb1 = ckpt1.get_embedding_state(0)
    assert emb1["word_embeddings.weight"].shape == (16, 8)
    norm = ckpt1.get_final_norm_state(0)
    assert norm["weight"].shape == (8,)


def test_migration_to_universal(tmp_path):
    d = _make_reference_ckpt(tmp_path, tp=2, n_layers=2, hidden=8)
    ckpt = DeepSpeedCheckpoint(str(d))
    out = ckpt.to_universal(str(tmp_path), tag="mig")
    from deepspeed_tpu.checkpoint import load_universal

    blob = load_universal(out)
    flat_keys = []

    def walk(t, p=""):
        for k, v in t.items():
            if isinstance(v, dict):
                walk(v, p + k + "/")
            else:
                flat_keys.append(p + k)

    walk(blob["fp32"])
    # qkv merged over tp: 3*8 = 24 rows
    qkv = [k for k in flat_keys if "query_key_value" in k]
    assert qkv

    def get(t, path):
        for p in path.split("/"):
            t = t[p]
        return t

    assert get(blob["fp32"], qkv[0]).shape == (24, 8)
    # row-parallel dense merged on dim 1
    dense = [k for k in flat_keys if "dense/weight" in k and "attention" in k]
    assert get(blob["fp32"], dense[0]).shape == (8, 8)
    assert blob["meta"]["step"] == 42
