"""Universal checkpoint × ZeRO-offload interaction (review-found gap):
moments must survive the round trip in BOTH directions (offload→offload and
offload→device), and params must come back in compute dtype."""

import numpy as np

import deepspeed_tpu as ds
from deepspeed_tpu.checkpoint import ds_to_universal, load_universal


def _engine(offload: bool, tmp=None):
    from deepspeed_tpu.parallel import initialize_mesh
    from deepspeed_tpu.parallel import mesh as mesh_mod

    from tests.unit.simple_model import SimpleModel

    mesh_mod.reset_mesh()
    config = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
        "bf16": {"enabled": True},
        "steps_per_print": 1000,
    }
    if offload:
        config["zero_optimization"]["offload_optimizer"] = {"device": "cpu"}
    engine, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                    config=config, mesh=initialize_mesh())
    return engine


def _batch(engine):
    rng = np.random.default_rng(0)
    return {"x": rng.standard_normal((engine.train_batch_size(), 16),
                                     dtype=np.float32),
            "y": rng.standard_normal((engine.train_batch_size(),),
                                     dtype=np.float32)}


def test_offload_universal_preserves_moments(tmp_path):
    engine = _engine(offload=True)
    b = _batch(engine)
    for _ in range(3):
        engine.train_batch(batch=b)
    engine.save_checkpoint(str(tmp_path))
    univ = ds_to_universal(str(tmp_path))
    blob = load_universal(univ)
    assert "exp_avg" in blob["opt"], list(blob["opt"])

    # moments in the universal file are param-shaped, not raveled
    def leaves(t):
        out = []

        def walk(x):
            if isinstance(x, dict):
                for v in x.values():
                    walk(v)
            else:
                out.append(x)

        walk(t)
        return out

    m_leaves = leaves(blob["opt"]["exp_avg"])
    assert any(l.ndim > 1 for l in m_leaves), \
        [l.shape for l in m_leaves]
    # a trained moment is non-zero
    assert any(np.abs(l).sum() > 0 for l in m_leaves)

    # offload → offload resume keeps the momentum (identical next loss)
    engine2 = _engine(offload=True)
    engine2.train_batch(batch=b)
    engine2.load_universal_checkpoint(str(tmp_path))
    m_restored = [a for a in engine2._offload_opt.m.values() if a is not None]
    assert any(np.abs(a).sum() > 0 for a in m_restored), \
        "moments silently re-zeroed on universal load"
    l1 = float(engine.train_batch(batch=b))
    l2 = float(engine2.train_batch(batch=b))
    assert np.isclose(l1, l2, rtol=1e-2), (l1, l2)


def test_offload_universal_loads_on_device_engine(tmp_path):
    engine = _engine(offload=True)
    b = _batch(engine)
    for _ in range(2):
        engine.train_batch(batch=b)
    engine.save_checkpoint(str(tmp_path))
    ds_to_universal(str(tmp_path))

    engine2 = _engine(offload=False)
    engine2.train_batch(batch=b)
    engine2.load_universal_checkpoint(str(tmp_path))
    # params restored in compute dtype (bf16), not raw fp32
    leaf = jax_leaf = None
    import jax

    for leaf in jax.tree_util.tree_leaves(engine2.state["params"]):
        break
    assert leaf.dtype == np.dtype("bfloat16") or str(leaf.dtype) == "bfloat16"
    l1 = float(engine.train_batch(batch=b))
    l2 = float(engine2.train_batch(batch=b))
    assert np.isclose(l1, l2, rtol=5e-2), (l1, l2)
