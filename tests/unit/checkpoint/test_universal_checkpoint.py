"""Universal checkpoint + zero_to_fp32 + orbax engine — analog of reference
``tests/unit/checkpoint/`` (universal/reshape/latest-tag suites)."""

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.checkpoint import (
    convert_zero_checkpoint_to_fp32_state_dict,
    ds_to_universal,
    get_fp32_state_dict_from_zero_checkpoint,
    load_universal,
)


def _make_engine(mesh_data=-1, zero_stage=1, fp16=False, offload=False):
    from deepspeed_tpu.parallel import initialize_mesh
    from deepspeed_tpu.parallel import mesh as mesh_mod

    mesh_mod.reset_mesh()
    mesh = initialize_mesh(data=mesh_data)
    from tests.unit.simple_model import SimpleModel

    config = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": zero_stage},
        "steps_per_print": 1000,
    }
    if fp16:
        config["fp16"] = {"enabled": True}
    if offload:
        config["zero_optimization"]["offload_optimizer"] = {"device": "cpu"}
    engine, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                    config=config, mesh=mesh)
    return engine


def _batch(engine, seed=0):
    rng = np.random.default_rng(seed)
    return {"x": rng.standard_normal((engine.train_batch_size(), 16),
                                     dtype=np.float32),
            "y": rng.standard_normal((engine.train_batch_size(),),
                                     dtype=np.float32)}


def test_universal_roundtrip_same_topology(tmp_path):
    engine = _make_engine()
    b = _batch(engine)
    for _ in range(3):
        engine.train_batch(batch=b)
    engine.save_checkpoint(str(tmp_path))
    univ = ds_to_universal(str(tmp_path))
    blob = load_universal(univ)
    assert blob["meta"]["global_steps"] == 3
    assert blob["fp32"], "fp32 weights must be present"

    engine2 = _make_engine()
    engine2.train_batch(batch=b)  # build state
    engine2.load_universal_checkpoint(str(tmp_path))
    assert engine2.global_steps == 3
    # training continues from the same weights → same next loss
    l1 = float(engine.train_batch(batch=b))
    l2 = float(engine2.train_batch(batch=b))
    assert np.isclose(l1, l2, rtol=1e-4), (l1, l2)


def test_universal_resize_topology(tmp_path):
    """Save at dp=8, load at dp=4×mp=2 — the elastic re-mesh path."""
    engine = _make_engine(mesh_data=8)
    b = _batch(engine)
    for _ in range(2):
        engine.train_batch(batch=b)
    engine.save_checkpoint(str(tmp_path))
    ds_to_universal(str(tmp_path))

    from deepspeed_tpu.parallel import initialize_mesh
    from deepspeed_tpu.parallel import mesh as mesh_mod

    mesh_mod.reset_mesh()
    mesh = initialize_mesh(data=4, model=2)
    from tests.unit.simple_model import SimpleModel

    engine2, _, _, _ = ds.initialize(
        model=SimpleModel(hidden_dim=16),
        config={"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": 1},
                "steps_per_print": 1000},
        mesh=mesh)
    b2 = {"x": b["x"], "y": b["y"]}
    engine2.train_batch(batch=b2)  # build state at new topology
    engine2.load_universal_checkpoint(str(tmp_path))
    assert engine2.global_steps == 2
    l1 = float(engine.train_batch(batch=b))
    l2 = float(engine2.train_batch(batch=b2))
    assert np.isclose(l1, l2, rtol=1e-3), (l1, l2)


def test_universal_with_fp16_master(tmp_path):
    engine = _make_engine(fp16=True)
    b = _batch(engine)
    for _ in range(2):
        engine.train_batch(batch=b)
    engine.save_checkpoint(str(tmp_path))
    univ = ds_to_universal(str(tmp_path))
    blob = load_universal(univ)
    # fp32 master + both Adam moments present
    assert blob["opt"], "expected optimizer moment trees"
    for tree in blob["fp32"].values():
        break
    engine2 = _make_engine(fp16=True)
    engine2.train_batch(batch=b)
    engine2.load_universal_checkpoint(str(tmp_path))
    l1 = float(engine.train_batch(batch=b))
    l2 = float(engine2.train_batch(batch=b))
    assert np.isclose(l1, l2, rtol=1e-3), (l1, l2)


def test_zero_to_fp32(tmp_path):
    engine = _make_engine(fp16=True)
    b = _batch(engine)
    engine.train_batch(batch=b)
    engine.save_checkpoint(str(tmp_path))
    sd = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path))
    assert all(v.dtype == np.float32 for v in sd.values())
    # dotted param names like linear_0.kernel
    assert any("kernel" in k for k in sd), list(sd)
    out = tmp_path / "consolidated.npz"
    convert_zero_checkpoint_to_fp32_state_dict(str(tmp_path), str(out))
    loaded = np.load(str(out))
    assert set(loaded.files) == set(sd.keys())


def test_config_load_universal_flag(tmp_path):
    engine = _make_engine()
    b = _batch(engine)
    engine.train_batch(batch=b)
    engine.save_checkpoint(str(tmp_path))
    ds_to_universal(str(tmp_path))

    from deepspeed_tpu.parallel import initialize_mesh
    from deepspeed_tpu.parallel import mesh as mesh_mod

    mesh_mod.reset_mesh()
    from tests.unit.simple_model import SimpleModel

    engine2, _, _, _ = ds.initialize(
        model=SimpleModel(hidden_dim=16),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "checkpoint": {"load_universal": True},
                "steps_per_print": 1000},
        mesh=initialize_mesh())
    engine2.train_batch(batch=b)
    engine2.load_checkpoint(str(tmp_path))  # routes through universal
    assert engine2.global_steps == 1


def test_orbax_engine_sharded_roundtrip(tmp_path, eight_device_mesh):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from deepspeed_tpu.runtime.checkpoint_engine.orbax_checkpoint_engine import (
        OrbaxCheckpointEngine,
    )

    mesh = eight_device_mesh
    sh = NamedSharding(mesh, PartitionSpec("data"))
    arr = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8), sh)
    tree = {"w": arr, "b": jnp.ones((3,), jnp.float32)}

    eng = OrbaxCheckpointEngine(use_async=True)
    path = str(tmp_path / "ckpt" / "state")
    eng.save({"arrays": tree, "meta": {"step": 7}}, path)
    eng.commit("tag")

    target = {"w": jax.ShapeDtypeStruct((8, 8), jnp.float32, sharding=sh),
              "b": jax.ShapeDtypeStruct((3,), jnp.float32)}
    out = eng.load(path, restore_target=target)
    assert out["meta"]["step"] == 7
    np.testing.assert_array_equal(np.asarray(out["arrays"]["w"]),
                                  np.asarray(arr))
    assert out["arrays"]["w"].sharding.is_equivalent_to(sh, 2)


def test_universal_from_orbax_layout(tmp_path):
    """ds_to_universal over a checkpoint saved through the ORBAX engine
    (the multi-process save layout: orbax_state dir + meta sidecar, no
    pickle files) — regression for the elastic-loop composition where a
    2-proc run's checkpoint must convert offline (VERDICT r2 #8)."""
    from deepspeed_tpu.runtime.checkpoint_engine.orbax_checkpoint_engine import (
        OrbaxCheckpointEngine,
    )

    engine = _make_engine()
    b = _batch(engine)
    for _ in range(2):
        engine.train_batch(batch=b)
    engine.checkpoint_engine = OrbaxCheckpointEngine(use_async=False)
    engine.save_checkpoint(str(tmp_path))
    import os

    tag = "global_step2"
    assert os.path.isdir(os.path.join(str(tmp_path), tag, "orbax_state"))
    assert not os.path.exists(os.path.join(
        str(tmp_path), tag, "mp_rank_00_model_states.meta"))

    univ = ds_to_universal(str(tmp_path))
    blob = load_universal(univ)
    assert blob["meta"]["global_steps"] == 2
    assert blob["fp32"], "fp32 weights missing from orbax conversion"
    assert blob["opt"], "optimizer moments missing from orbax conversion"

    engine2 = _make_engine()
    engine2.train_batch(batch=b)
    engine2.load_universal_checkpoint(str(tmp_path))
    assert engine2.global_steps == 2
    l1 = float(engine.train_batch(batch=b))
    l2 = float(engine2.train_batch(batch=b))
    assert np.isclose(l1, l2, rtol=1e-3), (l1, l2)
