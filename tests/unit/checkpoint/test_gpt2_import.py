"""Real-checkpoint GPT-2 migration: a reference-format (Megatron-DeepSpeed)
checkpoint, TP-sharded with torch, imports into the flax GPT-2 and produces
IDENTICAL logits whether read from tp=2 shards or the unsharded original —
the VERDICT done-criterion for AutoTP/state-dict-factory validation
(reference module_inject/auto_tp.py:13, runtime/state_dict_factory.py:190).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from deepspeed_tpu.checkpoint import megatron_gpt2_to_flax  # noqa: E402
from deepspeed_tpu.models.gpt2 import (  # noqa: E402
    GPT2Config,
    GPT2LMHeadModel,
    gpt2_sharding_rules,
)

HIDDEN, LAYERS, HEADS, VOCAB, POS = 16, 2, 2, 32, 16


def _full_weights(seed=0):
    """One set of full (unsharded) torch GPT-2 weights."""
    g = torch.Generator().manual_seed(seed)
    r = lambda *shape: torch.randn(*shape, generator=g) * 0.05  # noqa: E731
    layers = []
    for _ in range(LAYERS):
        layers.append({
            "input_layernorm.weight": torch.ones(HIDDEN),
            "input_layernorm.bias": r(HIDDEN),
            "self_attention.query_key_value.weight": r(3 * HIDDEN, HIDDEN),
            "self_attention.query_key_value.bias": r(3 * HIDDEN),
            "self_attention.dense.weight": r(HIDDEN, HIDDEN),
            "self_attention.dense.bias": r(HIDDEN),
            "post_attention_layernorm.weight": torch.ones(HIDDEN),
            "post_attention_layernorm.bias": r(HIDDEN),
            "mlp.dense_h_to_4h.weight": r(4 * HIDDEN, HIDDEN),
            "mlp.dense_h_to_4h.bias": r(4 * HIDDEN),
            "mlp.dense_4h_to_h.weight": r(HIDDEN, 4 * HIDDEN),
            "mlp.dense_4h_to_h.bias": r(HIDDEN),
        })
    return {
        "embedding": {"word_embeddings.weight": r(VOCAB, HIDDEN),
                      "position_embeddings.weight": r(POS, HIDDEN)},
        "layers": layers,
        "final_norm": {"weight": torch.ones(HIDDEN), "bias": r(HIDDEN)},
    }


def _shard(full, tp):
    """Megatron TP sharding conventions in torch (out, in) layout:
    qkv & h_to_4h row-split (column-parallel), dense & 4h_to_h col-split
    (row-parallel), embeddings vocab-split, norms replicated.

    qkv uses the REAL version-0 Megatron layout: rank r's shard is
    [q_r | k_r | v_r] fused — NOT a contiguous row chunk of the fused
    matrix. A naive dim-0 merge scrambles this; the importer must regroup
    per component (this is what makes the parity tests meaningful)."""
    def rows(t):  # split dim 0
        return torch.chunk(t, tp, dim=0)

    def cols(t):  # split dim 1
        return torch.chunk(t, tp, dim=1)

    def qkv_shard(t, r):
        q, k, v = torch.chunk(t, 3, dim=0)
        return torch.cat([rows(q)[r], rows(k)[r], rows(v)[r]], dim=0)

    shards = []
    for r in range(tp):
        layers = []
        for layer in full["layers"]:
            layers.append({
                "input_layernorm.weight": layer["input_layernorm.weight"],
                "input_layernorm.bias": layer["input_layernorm.bias"],
                "self_attention.query_key_value.weight":
                    qkv_shard(layer["self_attention.query_key_value.weight"],
                              r),
                "self_attention.query_key_value.bias":
                    qkv_shard(layer["self_attention.query_key_value.bias"],
                              r),
                "self_attention.dense.weight":
                    cols(layer["self_attention.dense.weight"])[r],
                "self_attention.dense.bias": layer["self_attention.dense.bias"],
                "post_attention_layernorm.weight":
                    layer["post_attention_layernorm.weight"],
                "post_attention_layernorm.bias":
                    layer["post_attention_layernorm.bias"],
                "mlp.dense_h_to_4h.weight":
                    rows(layer["mlp.dense_h_to_4h.weight"])[r],
                "mlp.dense_h_to_4h.bias":
                    rows(layer["mlp.dense_h_to_4h.bias"])[r],
                "mlp.dense_4h_to_h.weight":
                    cols(layer["mlp.dense_4h_to_h.weight"])[r],
                "mlp.dense_4h_to_h.bias": layer["mlp.dense_4h_to_h.bias"],
            })
        shards.append({
            "embedding": {
                "word_embeddings.weight":
                    rows(full["embedding"]["word_embeddings.weight"])[r],
                "position_embeddings.weight":
                    full["embedding"]["position_embeddings.weight"],
            },
            "layers": layers,
            "final_norm": dict(full["final_norm"]),
        })
    return shards


def _write_ckpt(dirpath, shards):
    """Reference layer-file layout: layer_00 embedding, 01..L transformer,
    L+1 final norm; one file per tp rank + mp_rank state files."""
    dirpath.mkdir(parents=True, exist_ok=True)
    tp = len(shards)
    last = LAYERS + 1
    for r, shard in enumerate(shards):
        torch.save(shard["embedding"],
                   dirpath / f"layer_00-model_{r:02d}-model_states.pt")
        for i, layer in enumerate(shard["layers"]):
            torch.save(layer,
                       dirpath / f"layer_{i + 1:02d}-model_{r:02d}"
                       f"-model_states.pt")
        torch.save(shard["final_norm"],
                   dirpath / f"layer_{last:02d}-model_{r:02d}"
                   f"-model_states.pt")
        torch.save({"iteration": 7},
                   dirpath / f"mp_rank_{r:02d}_model_states.pt")
    return dirpath


@pytest.fixture
def cfg():
    return GPT2Config(vocab_size=VOCAB, n_positions=POS, n_embd=HIDDEN,
                      n_layer=LAYERS, n_head=HEADS, dtype=jnp.float32)


def _logits(cfg, params, ids):
    model = GPT2LMHeadModel(cfg)
    return np.asarray(model.apply({"params": params}, ids,
                                  method=GPT2LMHeadModel.logits))


def test_tp2_shards_match_unsharded_logits(tmp_path, cfg):
    full = _full_weights()
    d1 = _write_ckpt(tmp_path / "tp1", _shard(full, 1))
    d2 = _write_ckpt(tmp_path / "tp2", _shard(full, 2))

    p1 = megatron_gpt2_to_flax(str(d1), cfg)
    p2 = megatron_gpt2_to_flax(str(d2), cfg)

    # the merge reconstructed every weight exactly
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)), p1, p2)

    ids = np.arange(8, dtype=np.int32)[None] % VOCAB
    np.testing.assert_allclose(_logits(cfg, p2, ids), _logits(cfg, p1, ids),
                               rtol=1e-6)


def test_imported_tree_matches_model_structure(tmp_path, cfg):
    d = _write_ckpt(tmp_path / "tp2", _shard(_full_weights(), 2))
    params = megatron_gpt2_to_flax(str(d), cfg)
    model = GPT2LMHeadModel(cfg)
    init = model.init({"params": jax.random.PRNGKey(0),
                       "dropout": jax.random.PRNGKey(0)},
                      {"input_ids": np.zeros((1, 4), np.int32)})["params"]
    init_paths = {jax.tree_util.keystr(kp): np.shape(leaf) for kp, leaf
                  in jax.tree_util.tree_leaves_with_path(init)}
    got_paths = {jax.tree_util.keystr(kp): np.shape(leaf) for kp, leaf
                 in jax.tree_util.tree_leaves_with_path(params)}
    assert got_paths == init_paths


def test_imported_params_run_sharded_tp2(tmp_path, cfg):
    """The migrated checkpoint actually trains/infers under tp=2: logits of
    the tp-sharded engine equal the unsharded apply."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import initialize_mesh, reset_mesh
    from deepspeed_tpu.runtime.zero.policy import ShardingRules

    d = _write_ckpt(tmp_path / "tp2", _shard(_full_weights(), 2))
    params = megatron_gpt2_to_flax(str(d), cfg)
    # batch rows divisible by dp=4
    ids = (np.arange(32, dtype=np.int32) % VOCAB).reshape(4, 8)
    expect = _logits(cfg, params, ids)

    reset_mesh()
    initialize_mesh(data=4, model=2)
    eng, _, _, _ = ds.initialize(
        model=GPT2LMHeadModel(cfg), model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 1,
                "zero_optimization": {"stage": 0},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        sharding_rules=ShardingRules(gpt2_sharding_rules()))
    loss = eng.forward({"input_ids": ids})
    assert np.isfinite(float(loss))
    sharded_logits = np.asarray(jax.device_get(jax.jit(
        lambda p, i: eng.module.apply({"params": p}, i,
                                      method=GPT2LMHeadModel.logits))(
            eng.state["params"], ids)))
    np.testing.assert_allclose(sharded_logits, expect, atol=2e-5, rtol=1e-4)
