"""Serving subsystem tests: continuous batching over the slot-pooled KV
cache must be a pure SCHEDULING change — per-request tokens bitwise-match
whole-batch ``generate()``, slot reuse never recompiles the decode step,
staggered arrivals admit/retire correctly, and admission control sheds
load with a reason instead of raising."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import FIFOScheduler, RequestState, ServingEngine

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


def test_tokens_bitwise_match_generate(stack):
    """Continuous batching through 2 slots (forcing multi-wave slot reuse)
    must produce EXACTLY the tokens static-batch generate() produces per
    prompt — scheduling policy can never change model output (greedy)."""
    _, _, engine = stack
    rng = np.random.default_rng(7)
    lengths = [5, 9, 12, 5, 9, 12]
    budgets = [6, 4, 8, 3, 7, 5]
    prompts = [rng.integers(0, 64, size=n).astype(np.int32) for n in lengths]

    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8)
    reqs = [srv.submit(p, max_new_tokens=b) for p, b in zip(prompts, budgets)]
    finished = srv.run_until_drained(max_steps=200)

    assert len(finished) == len(reqs)
    for req, prompt, budget in zip(reqs, prompts, budgets):
        assert req.state == RequestState.FINISHED
        assert req.finish_reason == "length"
        expected = engine.generate(prompt[None], max_new_tokens=budget)[0]
        np.testing.assert_array_equal(req.tokens(), expected,
                                      err_msg=f"req {req.request_id}")


def test_staggered_admission_and_slot_reuse(stack):
    """A request submitted while all slots are busy waits QUEUED, then is
    admitted into the retired request's slot; timing stamps are ordered."""
    _, _, engine = stack
    rng = np.random.default_rng(3)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8)
    r1 = srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                    max_new_tokens=2)
    r2 = srv.submit(rng.integers(0, 64, size=10).astype(np.int32),
                    max_new_tokens=12)
    done = srv.step()  # admit both; r1 (budget 2) finishes on this step
    assert r1 in done and r1.state == RequestState.FINISHED
    assert r2.state == RequestState.RUNNING

    r3 = srv.submit(rng.integers(0, 64, size=7).astype(np.int32),
                    max_new_tokens=4)
    assert r3.state == RequestState.QUEUED and srv.pending == 1
    srv.step()  # admits r3 into r1's freed slot
    assert r3.state == RequestState.RUNNING
    assert r3.slot == r1.slot

    srv.run_until_drained(max_steps=50)
    for r in (r1, r2, r3):
        assert r.state == RequestState.FINISHED
        assert r.submit_time <= r.admit_time <= r.first_token_time \
            <= r.finish_time
        assert r.queue_wait >= 0 and r.ttft >= 0
        assert len(r.output_tokens) == r.max_new_tokens


def test_slot_reuse_does_not_recompile(stack):
    """Retire/admit churn across waves must keep the jitted decode and
    prefill caches at a FIXED number of compiled programs — dead slots are
    masked padding, not shape changes."""
    _, _, engine = stack
    rng = np.random.default_rng(5)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=16)
    # wave A: compile everything once — 3 requests over 2 slots so both
    # admission batch buckets (nB=2 full step, nB=1 single refill) warm up
    for _ in range(3):
        srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                   max_new_tokens=3)
    srv.run_until_drained(max_steps=50)
    n_decode = engine._jit_decode._cache_size()
    n_prefill = engine._jit_prefill_at._cache_size()
    srv.end_warmup()  # arm the watchdog's post-warmup counter

    for _ in range(5):  # wave B: same buckets through reused slots
        srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                   max_new_tokens=4)
    srv.run_until_drained(max_steps=100)
    assert engine._jit_decode._cache_size() == n_decode
    assert engine._jit_prefill_at._cache_size() == n_prefill
    assert srv.watchdog.recompiles == 0


def test_admission_control_rejects_with_reason(stack):
    _, _, engine = stack
    rng = np.random.default_rng(11)
    srv = ServingEngine(engine, num_slots=1, max_queue_depth=2)

    ok = [srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
                     max_new_tokens=2) for _ in range(2)]
    full = srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
                      max_new_tokens=2)
    assert full.state == RequestState.REJECTED
    assert full.reject_reason == "queue_full"

    # prompt + budget exceeding KV capacity is rejected up front, not
    # admitted into a slot it can never finish in
    long = srv.submit(rng.integers(0, 64, size=60).astype(np.int32),
                      max_new_tokens=10)
    assert long.state == RequestState.REJECTED
    assert long.reject_reason == "prompt_too_long"

    with pytest.raises(ValueError, match="max_new_tokens"):
        srv.submit(np.zeros((4,), np.int32), max_new_tokens=0)

    srv.run_until_drained(max_steps=50)
    assert all(r.state == RequestState.FINISHED for r in ok)
    stats = srv.stats()
    assert stats["completed"] == 2
    assert stats["rejected"] == {"queue_full": 1, "prompt_too_long": 1}


def test_eos_retires_early(stack):
    """With eos_token_id set, a slot retires the moment greedy emits it —
    and the emitted prefix still matches generate()'s."""
    _, _, engine = stack
    rng = np.random.default_rng(13)
    prompt = rng.integers(0, 64, size=8).astype(np.int32)
    full = engine.generate(prompt[None], max_new_tokens=8)[0]
    gen = np.asarray(full[len(prompt):])
    eos = int(gen[2])  # greedy will deterministically reach this token
    first = int(np.argmax(gen == eos))

    srv = ServingEngine(engine, num_slots=1, max_queue_depth=2)
    req = srv.submit(prompt, max_new_tokens=8, eos_token_id=eos)
    srv.run_until_drained(max_steps=50)
    assert req.finish_reason == "eos"
    assert req.output_tokens[-1] == eos
    np.testing.assert_array_equal(req.output_tokens, gen[:first + 1])


def test_gang_policy_is_batch_synchronous(stack):
    """The bench baseline arm: gang admission refuses to backfill free
    slots until the WHOLE wave has drained (the generate() discipline)."""
    _, _, engine = stack
    rng = np.random.default_rng(17)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                        policy="gang")
    r1 = srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
                    max_new_tokens=2)
    r2 = srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
                    max_new_tokens=6)
    r3 = srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
                    max_new_tokens=2)
    srv.step()  # wave 1 admitted (r1, r2); r1 finishes (budget 2)
    assert r1.state == RequestState.FINISHED
    while srv.live_count:  # r3 must NOT be admitted while r2 runs
        assert r3.state == RequestState.QUEUED
        srv.step()
    srv.run_until_drained(max_steps=50)
    assert r3.state == RequestState.FINISHED
    # and the policy changed nothing about the tokens
    expected = engine.generate(np.asarray(r3.prompt)[None],
                               max_new_tokens=2)[0]
    np.testing.assert_array_equal(r3.tokens(), expected)


def test_scheduler_unit():
    sched = FIFOScheduler(num_slots=2, max_queue_depth=2, policy="continuous",
                          capacity=32)
    with pytest.raises(ValueError, match="policy"):
        FIFOScheduler(2, 2, policy="nope", capacity=32)

    class R:  # minimal stand-in (the admission surface of Request:
        # capacity charges seed + REMAINING budget, see Scheduler.submit)
        def __init__(self, n, m):
            self.prompt_len, self.max_new_tokens = n, m
            self.output_tokens = []
            self.seed_len = n

    ok, _ = sched.submit(R(4, 4))
    assert ok
    ok, reason = sched.submit(R(30, 8))
    assert not ok and reason == "prompt_too_long"
    sched.submit(R(4, 4))
    ok, reason = sched.submit(R(4, 4))
    assert not ok and reason == "queue_full"
    assert len(sched.grant(free_slots=2, live_slots=0)) == 2
    assert sched.pending == 0


def test_init_serving_wrapper(stack):
    """ds.init_serving splits serving knobs from inference knobs."""
    model, params, _ = stack
    srv = ds.init_serving(model, config={"dtype": "float32"},
                          model_parameters=params, num_slots=2,
                          max_queue_depth=4, policy="gang", seed=3)
    assert isinstance(srv, ServingEngine)
    assert srv.scheduler.policy == "gang"
    assert srv.pool.num_slots == 2
    req = srv.submit(np.arange(5, dtype=np.int32), max_new_tokens=2)
    srv.run_until_drained(max_steps=20)
    assert req.state == RequestState.FINISHED


def test_release_double_free_guard(stack):
    """Releasing a freed (or out-of-range) slot raises instead of
    silently corrupting the free heap into double-granting a slot."""
    _, _, engine = stack
    from deepspeed_tpu.serving import SlotPool
    pool = SlotPool(engine.kv_cache_spec(), 2)
    s = pool.alloc()
    pool.release(s)
    with pytest.raises(RuntimeError, match="double release"):
        pool.release(s)
    assert pool.free_count == 2  # the guard fired before corrupting
    with pytest.raises(ValueError, match="range"):
        pool.release(7)


def test_midstep_decode_exception_never_leaks_slots(stack):
    """An engine exception mid-decode must FAIL the running requests
    (their donated KV state is unrecoverable), keep queued requests
    queued, return every slot, and leave the server usable."""
    _, _, engine = stack
    rng = np.random.default_rng(41)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8)
    r1 = srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                    max_new_tokens=6)
    r2 = srv.submit(rng.integers(0, 64, size=9).astype(np.int32),
                    max_new_tokens=6)
    r3 = srv.submit(rng.integers(0, 64, size=7).astype(np.int32),
                    max_new_tokens=4)  # no free slot: stays QUEUED
    srv.step()
    assert r1.state == r2.state == RequestState.RUNNING

    orig = engine._jit_decode
    engine._jit_decode = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("injected decode failure"))
    try:
        with pytest.raises(RuntimeError, match="injected"):
            srv.step()
    finally:
        engine._jit_decode = orig

    assert srv.live_count == 0 and srv.pool.free_count == 2
    for r in (r1, r2):
        assert r.state == RequestState.FAILED
        assert r.finish_reason == "error" and r.finish_time is not None
    assert r3.state == RequestState.QUEUED  # survives the abort

    srv.run_until_drained(max_steps=50)    # server still works
    assert r3.state == RequestState.FINISHED
    expected = engine.generate(np.asarray(r3.prompt)[None],
                               max_new_tokens=4)[0]
    np.testing.assert_array_equal(r3.tokens(), expected)
    assert srv.stats()["failed"] == 2


def test_admit_exception_requeues_request(stack):
    """A prefill exception during admission rolls the request back to
    QUEUED (front of queue, state scrubbed) instead of leaking its slot
    or failing it — it lost nothing but time."""
    _, _, engine = stack
    rng = np.random.default_rng(43)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8)
    prompt = rng.integers(0, 64, size=6).astype(np.int32)
    r1 = srv.submit(prompt, max_new_tokens=3)

    orig = engine._jit_prefill_at
    engine._jit_prefill_at = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("injected prefill failure"))
    try:
        with pytest.raises(RuntimeError, match="injected"):
            srv.step()
    finally:
        engine._jit_prefill_at = orig

    assert r1.state == RequestState.QUEUED and srv.pending == 1
    assert srv.pool.free_count == 2 and srv.live_count == 0
    assert r1.slot is None and r1.output_tokens == []
    assert r1.admit_time is None and r1.first_token_time is None

    srv.run_until_drained(max_steps=50)
    assert r1.state == RequestState.FINISHED
    expected = engine.generate(prompt[None], max_new_tokens=3)[0]
    np.testing.assert_array_equal(r1.tokens(), expected)
    assert srv.stats()["failed"] == 0


class _FakeMonitor:
    enabled = True

    def __init__(self):
        self.events = []

    def write_events(self, events):
        self.events.extend(events)


def test_rejection_paths_end_to_end_with_metrics(stack):
    """queue_full / prompt_too_long shedding: the request never consumes
    a slot, the reason lands in stats() AND as a monitor event, and the
    accepted workload is unaffected."""
    _, _, engine = stack
    rng = np.random.default_rng(47)
    mon = _FakeMonitor()
    srv = ServingEngine(engine, num_slots=1, max_queue_depth=1, monitor=mon)

    ok = srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
                    max_new_tokens=2)
    full = srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
                      max_new_tokens=2)
    long = srv.submit(rng.integers(0, 64, size=60).astype(np.int32),
                      max_new_tokens=10)
    assert full.state == RequestState.REJECTED
    assert full.reject_reason == "queue_full"
    assert long.state == RequestState.REJECTED
    assert long.reject_reason == "prompt_too_long"
    # shedding happened at submit: no slot was ever consumed
    assert srv.pool.free_count == 1 and srv.live_count == 0
    tags = [t for t, _, _ in mon.events]
    assert tags.count("serving/rejected/queue_full") == 1
    assert tags.count("serving/rejected/prompt_too_long") == 1

    srv.run_until_drained(max_steps=20)
    assert ok.state == RequestState.FINISHED
    s = srv.stats()
    assert s["completed"] == 1
    assert s["rejected"] == {"queue_full": 1, "prompt_too_long": 1}
    assert "serving/ttft_ms" in [t for t, _, _ in mon.events]


def test_metrics_snapshot_fields(stack):
    _, _, engine = stack
    rng = np.random.default_rng(19)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8)
    for _ in range(3):
        srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                   max_new_tokens=3)
    srv.run_until_drained(max_steps=50)
    s = srv.stats()
    assert s["completed"] == 3
    assert s["new_tokens"] == 9
    assert s["requests_per_s"] > 0 and s["tokens_per_s"] > 0
    for k in ("ttft_p50_ms", "ttft_p99_ms", "queue_wait_p50_ms",
              "per_token_p50_ms", "per_token_p99_ms"):
        assert np.isfinite(s[k]) and s[k] >= 0, k
    # plain decode: exactly one token per live slot per step, no spec
    assert s["tokens_per_decode_step"] == 1.0
    assert s["failed"] == 0 and s["spec_drafted"] == 0
    assert s["spec_acceptance_rate"] is None
