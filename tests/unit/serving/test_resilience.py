"""Fault-tolerance & chaos tests for the serving engine: deadlines,
preemption (bitwise-identical resume), graceful degradation, the
deterministic fault injector, and the cross-bookkeeping invariant
audit. The contract under test: NO fault, wherever injected, may leak
a slot, strand a request without a terminal reason, or change the
compiled program set — and a preempted greedy request's output is
bitwise what it would have been without the preemption."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import (FIFOScheduler, FinishReason, RejectReason,
                                   Request, RequestState, ServingEngine)
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.resilience import (DegradationConfig,
                                              FaultInjector, InjectedFault,
                                              LoadState, ServingStalledError)
from deepspeed_tpu.serving.resilience.degradation import LoadStateMachine
from deepspeed_tpu.serving.resilience.preemption import select_victims

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


def _prompts(rng, n, lo=5, hi=12):
    return [rng.integers(0, 64, size=int(rng.integers(lo, hi + 1)))
            .astype(np.int32) for _ in range(n)]


def _assert_clean(srv):
    """The post-fault contract: bookkeeping consistent, no leaked slot,
    every timeline terminal."""
    srv.check_invariants()
    assert srv.pool.free_count == srv.pool.num_slots
    assert srv.live_count == 0
    assert srv.timelines.open_ids() == []


# ---------------------------------------------------------------------------
# fault injector (no model needed)
# ---------------------------------------------------------------------------
class TestFaultInjector:
    def test_schedule_fires_exact_ordinals(self):
        fi = FaultInjector(seed=0, schedule={"admit_oom": [2, 4]})
        fired = []
        for _ in range(5):
            try:
                fi.check("admit_oom")
                fired.append(False)
            except InjectedFault as e:
                assert e.point == "admit_oom"
                fired.append(True)
        assert fired == [False, True, False, True, False]
        assert fi.counts["admit_oom"] == 5 and fi.fired["admit_oom"] == 2

    def test_schedule_ordinals_are_one_based(self):
        with pytest.raises(ValueError, match="1-based"):
            FaultInjector(schedule={"admit_oom": [0]})

    def test_unknown_point_rejected(self):
        with pytest.raises(ValueError, match="unknown injection point"):
            FaultInjector(schedule={"disk_full": [1]})
        with pytest.raises(ValueError, match="unknown injection point"):
            FaultInjector().check("disk_full")

    def test_rate_streams_deterministic_and_per_point(self):
        def pattern(seed, point, n=64):
            fi = FaultInjector(seed=seed, rates={point: 0.5})
            return [fi._roll(point) for _ in range(n)]

        a = pattern(7, "nan_logits")
        assert a == pattern(7, "nan_logits")          # replayable
        assert a != pattern(8, "nan_logits")          # seed matters
        # independent stream per point: same seed, different point,
        # different draws
        assert a != pattern(7, "drafter_error")

    def test_load_schedule_resets_counts(self):
        fi = FaultInjector(schedule={"admit_oom": [1]})
        with pytest.raises(InjectedFault):
            fi.check("admit_oom")
        fi.load_schedule({"admit_oom": [1]})
        assert fi.counts["admit_oom"] == 0
        with pytest.raises(InjectedFault):    # ordinal 1 re-armed
            fi.check("admit_oom")

    def test_maybe_sleep_only_fires_on_schedule(self):
        fi = FaultInjector(schedule={"slow_dispatch": [2]}, slow_ms=0.0)
        assert fi.maybe_sleep() is False
        assert fi.maybe_sleep() is True


# ---------------------------------------------------------------------------
# reason enums (satellite: every monitor event uses them)
# ---------------------------------------------------------------------------
class TestReasonEnums:
    def test_finish_reason_str_mixin(self):
        assert FinishReason.DEADLINE == "deadline"
        assert str(FinishReason.NUMERICAL_ERROR) == "numerical_error"
        assert f"{FinishReason.EOS}" == "eos"
        assert FinishReason.of("length") is FinishReason.LENGTH
        assert FinishReason.of(FinishReason.ERROR) is FinishReason.ERROR
        with pytest.raises(ValueError):
            FinishReason.of("melted")

    def test_reject_reason_roundtrip(self):
        assert RejectReason.of("retry_after") is RejectReason.RETRY_AFTER
        with pytest.raises(ValueError):
            RejectReason.of("because")

    def test_metrics_reject_unknown_reasons(self):
        m = ServingMetrics(None)
        req = Request(0, np.arange(4, dtype=np.int32), 4, None)
        req.reject_reason = "bogus"
        with pytest.raises(ValueError):
            m.record_rejection(req)
        req.reject_reason = RejectReason.QUEUE_FULL
        m.record_rejection(req)     # enum member: accepted
        bad = Request(1, np.arange(4, dtype=np.int32), 4, None)
        bad.finish_reason = "imploded"
        with pytest.raises(ValueError):
            m.record_failure(bad)


# ---------------------------------------------------------------------------
# scheduler hardening (satellite: requeue_front FIFO regression)
# ---------------------------------------------------------------------------
class TestSchedulerResilience:
    @staticmethod
    def _req(i, out=0):
        r = Request(i, np.arange(4, dtype=np.int32), 8, None)
        r.output_tokens = list(range(out))
        return r

    def test_requeue_front_preserves_relative_order(self):
        # the FIFO-inversion regression: requeue_front([a, b]) with [c]
        # already queued must pop a, b, c — never b, a, c
        s = FIFOScheduler(2, max_queue_depth=8)
        a, b, c = (self._req(i) for i in range(3))
        s.submit(c)
        s.requeue_front([a, b])
        assert [r.request_id for r in s.queue] == [0, 1, 2]
        assert all(r.state is RequestState.QUEUED for r in (a, b))

    def test_requeue_back_appends_tail(self):
        s = FIFOScheduler(2, max_queue_depth=8)
        a, b = self._req(0), self._req(1)
        s.submit(a)
        s.requeue_back([b])
        assert [r.request_id for r in s.queue] == [0, 1]

    def test_expire_removes_only_expired(self):
        s = FIFOScheduler(2, max_queue_depth=8)
        a, b = self._req(0), self._req(1)
        a.deadline_time = 10.0
        b.deadline_time = 30.0
        s.submit(a)
        s.submit(b)
        gone = s.expire(now=20.0)
        assert gone == [a]
        assert list(s.queue) == [b]

    def test_capacity_accounts_resumed_seed(self):
        # a preempted request's footprint is seed + REMAINING budget;
        # one that can no longer fit is refused, not admitted to die
        s = FIFOScheduler(2, max_queue_depth=8, capacity=16)
        r = self._req(0, out=10)    # seed = 4 prompt + 10 generated = 14
        r.max_new_tokens = 12       # 2 remaining -> 16 total: fits
        assert s.submit(r) == (True, None)
        r2 = self._req(1, out=10)
        r2.max_new_tokens = 13      # 3 remaining -> 17 total: too long
        ok, why = s.submit(r2)
        assert not ok and why is RejectReason.PROMPT_TOO_LONG


class TestVictimSelection:
    @staticmethod
    def _seated(i, tokens, admit_step):
        r = Request(i, np.arange(4, dtype=np.int32), 32, None)
        r.state = RequestState.RUNNING
        r.output_tokens = list(range(tokens))
        r.last_admit_step = admit_step
        return r

    def test_youngest_lowest_progress_first(self):
        old = self._seated(0, tokens=9, admit_step=0)
        young = self._seated(1, tokens=2, admit_step=3)
        younger = self._seated(2, tokens=2, admit_step=5)
        got = select_victims([old, young, younger], n=2, current_step=20)
        assert [r.request_id for r in got] == [2, 1]

    def test_min_run_steps_protects_fresh_seats(self):
        fresh = self._seated(0, tokens=0, admit_step=9)
        settled = self._seated(1, tokens=5, admit_step=0)
        assert select_victims([fresh, settled], n=2, current_step=10,
                              min_run_steps=2) == [settled]
        # queued / terminal states are never victims
        q = self._seated(2, tokens=0, admit_step=0)
        q.state = RequestState.QUEUED
        assert select_victims([q], current_step=10) == []


class TestLoadStateMachine:
    def test_escalates_immediately_deescalates_after_cooldown(self):
        cfg = DegradationConfig.from_value(
            {"queue_pressured": 2, "queue_overloaded": 4,
             "cooldown_steps": 3})
        m = LoadStateMachine(cfg)
        assert m.update(4, None, step=0) == (LoadState.HEALTHY,
                                             LoadState.OVERLOADED)
        # calm observations: no transition until cooldown_steps of them
        assert m.update(0, None, step=1) is None
        assert m.update(0, None, step=2) is None
        # ...and de-escalation goes straight to the observed level
        assert m.update(0, None, step=3) == (LoadState.OVERLOADED,
                                             LoadState.HEALTHY)
        assert [t[1:] for t in m.transitions] == [
            (LoadState.HEALTHY, LoadState.OVERLOADED),
            (LoadState.OVERLOADED, LoadState.HEALTHY)]

    def test_worst_signal_wins_and_config_validates(self):
        cfg = DegradationConfig.from_value(
            {"queue_pressured": 8, "queue_overloaded": 16,
             "gap_p99_pressured_ms": 5.0, "gap_p99_overloaded_ms": 50.0})
        m = LoadStateMachine(cfg)
        assert m.classify(0, 7.0) is LoadState.PRESSURED
        assert m.classify(20, 0.0) is LoadState.OVERLOADED
        with pytest.raises(ValueError):
            DegradationConfig.from_value({"queue_pressured": 9,
                                          "queue_overloaded": 4})
        with pytest.raises(ValueError):
            DegradationConfig.from_value({"nope": 1})
        assert DegradationConfig.from_value(None) is None
        assert DegradationConfig.from_value(True).queue_pressured == 8


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------
class TestDeadlines:
    def test_queued_request_expires_before_costing_prefill(self, stack):
        _, _, engine = stack
        rng = np.random.default_rng(0)
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=8)
        req = srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                         max_new_tokens=8, deadline_ms=1.0)
        time.sleep(0.01)
        srv.step()
        assert req.state is RequestState.FINISHED
        assert req.finish_reason is FinishReason.DEADLINE
        assert req.output_tokens == [] and req.slot is None
        assert srv.stats()["deadline_expired"] == 1
        _assert_clean(srv)

    def test_seated_request_retires_via_rollback_path(self, stack):
        _, _, engine = stack
        rng = np.random.default_rng(1)
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=8)
        req = srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                         max_new_tokens=32, deadline_ms=60_000.0)
        srv.step()
        srv.step()
        assert req.state is RequestState.RUNNING
        got = len(req.output_tokens)
        assert got >= 1
        req.deadline_time = srv._now() - 1.0   # force expiry
        srv.step()
        assert req.state is RequestState.FINISHED
        assert req.finish_reason is FinishReason.DEADLINE
        assert len(req.output_tokens) == got   # partial output preserved
        _assert_clean(srv)

    def test_engine_default_ttl_applies(self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=2, deadline_default_ms=500.0)
        req = srv.submit(np.arange(5, dtype=np.int32), max_new_tokens=2)
        assert req.deadline_ms == 500.0 and req.deadline_time is not None
        srv.run_until_drained(max_steps=30)
        assert req.finish_reason is FinishReason.LENGTH


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------
class TestPreemption:
    def test_preempted_output_bitwise_identical(self, stack):
        """The headline resume guarantee: preempt mid-generation, resume
        through re-prefill, and the greedy token stream is EXACTLY what
        an unpreempted run produces."""
        _, _, engine = stack
        rng = np.random.default_rng(2)
        prompt = rng.integers(0, 64, size=9).astype(np.int32)
        budget = 12
        expected = engine.generate(prompt[None], max_new_tokens=budget)[0]

        srv = ServingEngine(engine, num_slots=2, max_queue_depth=8)
        req = srv.submit(prompt, max_new_tokens=budget)
        for _ in range(4):
            srv.step()
        assert req.state is RequestState.RUNNING
        mid = len(req.output_tokens)
        assert 0 < mid < budget

        srv.preempt(req.request_id)
        assert req.state is RequestState.QUEUED and req.slot is None
        assert req.preemptions == 1
        assert len(req.output_tokens) == mid   # generated work carried
        srv.check_invariants()

        srv.run_until_drained(max_steps=100)
        assert req.state is RequestState.FINISHED
        np.testing.assert_array_equal(req.tokens(), expected)
        assert srv.stats()["preempted"] == 1
        _assert_clean(srv)

    def test_preempt_requeues_front_of_line(self, stack):
        _, _, engine = stack
        rng = np.random.default_rng(3)
        srv = ServingEngine(engine, num_slots=1, max_queue_depth=8)
        victim = srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
                            max_new_tokens=16)
        waiter = srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
                            max_new_tokens=4)
        srv.step()
        assert victim.state is RequestState.RUNNING
        srv.preempt(victim.request_id)
        # manual preemption goes to the HEAD: the operator's victim
        # resumes before requests that were already waiting behind it
        assert [r.request_id for r in srv.scheduler.queue] == \
            [victim.request_id, waiter.request_id]

    def test_preempt_unknown_id_raises(self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=2)
        with pytest.raises(ValueError, match="not seated"):
            srv.preempt(12345)

    def test_auto_preemption_under_pressure_still_exact(self, stack):
        """Queue pressure past the threshold triggers automatic victim
        eviction (requeued at the TAIL — time-slicing, not a swap
        livelock) and every request still finishes with bitwise-exact
        greedy output."""
        _, _, engine = stack
        rng = np.random.default_rng(4)
        prompts = _prompts(rng, 6)
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=16,
                            preempt_queue_threshold=2,
                            preempt_min_run_steps=2)
        reqs = [srv.submit(p, max_new_tokens=6) for p in prompts]
        srv.run_until_drained(max_steps=400)
        assert srv.stats()["preempted"] >= 1
        for req, prompt in zip(reqs, prompts):
            assert req.state is RequestState.FINISHED
            expected = engine.generate(prompt[None], max_new_tokens=6)[0]
            np.testing.assert_array_equal(req.tokens(), expected)
        _assert_clean(srv)

    def test_preempt_mid_chunked_prefill(self, stack):
        """A PREFILLING victim restarts its chunk walk from zero on
        resume; output parity still holds."""
        _, _, engine = stack
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, 64, size=40).astype(np.int32)
        srv = ServingEngine(engine, num_slots=2, prefill_chunk=16,
                            prefill_token_budget=16)
        req = srv.submit(prompt, max_new_tokens=6)
        srv.step()
        assert req.state is RequestState.PREFILLING
        srv.preempt(req.request_id)
        assert req.state is RequestState.QUEUED and req.prefill_pos == 0
        srv.check_invariants()
        srv.run_until_drained(max_steps=100)
        expected = engine.generate(prompt[None], max_new_tokens=6)[0]
        np.testing.assert_array_equal(req.tokens(), expected)
        _assert_clean(srv)


# ---------------------------------------------------------------------------
# graceful degradation
# ---------------------------------------------------------------------------
class TestDegradation:
    def test_ladder_walks_and_sheds_with_retry_after(self, stack):
        _, _, engine = stack
        rng = np.random.default_rng(6)
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=32,
                            degradation={"queue_pressured": 2,
                                         "queue_overloaded": 4,
                                         "cooldown_steps": 2,
                                         "retry_after_s": 0.25})
        reqs = [srv.submit(p, max_new_tokens=4) for p in _prompts(rng, 6)]
        srv.step()   # boundary sees queue depth >= 4 -> OVERLOADED
        assert srv._load.state is LoadState.OVERLOADED
        shed = srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
                          max_new_tokens=4)
        assert shed.state is RequestState.REJECTED
        assert shed.reject_reason is RejectReason.RETRY_AFTER
        assert shed.retry_after_s == 0.25
        srv.run_until_drained(max_steps=200)
        stats = srv.stats()
        assert stats["load_transitions"] >= 2    # up AND back down
        assert stats["rejected"].get("retry_after") == 1
        for r in reqs:
            assert r.state is RequestState.FINISHED
        _assert_clean(srv)

    def test_pressure_shrinks_prefill_budget(self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=2, prefill_chunk=16,
                            prefill_token_budget=64,
                            degradation={"queue_pressured": 1,
                                         "queue_overloaded": 8})
        assert srv._effective_prefill_budget() == 64
        srv._load.state = LoadState.PRESSURED
        assert srv._effective_prefill_budget() == 32
        srv._load.state = LoadState.OVERLOADED
        assert srv._effective_prefill_budget() == 16   # one chunk

    def test_overload_suspends_spec_drafting(self, stack):
        """OVERLOADED pushes zero-length drafts through the SAME verify
        program — throughput degrades, shapes (and greedy output) do
        not."""
        _, _, engine = stack
        rng = np.random.default_rng(7)
        prompts = _prompts(rng, 4)
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=16,
                            spec_decode={"drafter": "ngram", "k": 4},
                            degradation={"queue_pressured": 1,
                                         "queue_overloaded": 2,
                                         "cooldown_steps": 64})
        reqs = [srv.submit(p, max_new_tokens=5) for p in prompts]
        srv.run_until_drained(max_steps=200)
        assert srv._load.state is not LoadState.HEALTHY  # ladder engaged
        for req, prompt in zip(reqs, prompts):
            assert req.state is RequestState.FINISHED
            expected = engine.generate(prompt[None], max_new_tokens=5)[0]
            np.testing.assert_array_equal(req.tokens(), expected)
        _assert_clean(srv)


# ---------------------------------------------------------------------------
# chaos: every injection point, invariants after each
# ---------------------------------------------------------------------------
class TestChaos:
    def test_admit_oom_rolls_back_and_recovers(self, stack):
        _, _, engine = stack
        rng = np.random.default_rng(8)
        prompts = _prompts(rng, 3)
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                            fault_injector=FaultInjector(
                                seed=0, schedule={"admit_oom": [1]}))
        reqs = [srv.submit(p, max_new_tokens=4) for p in prompts]
        with pytest.raises(InjectedFault):
            srv.step()
        srv.check_invariants()
        assert srv.pool.free_count == 2          # rolled back, no leak
        assert all(r.state is RequestState.QUEUED for r in reqs)
        srv.run_until_drained(max_steps=100)     # ordinal consumed: clean
        for req, prompt in zip(reqs, prompts):
            assert req.state is RequestState.FINISHED
            expected = engine.generate(prompt[None], max_new_tokens=4)[0]
            np.testing.assert_array_equal(req.tokens(), expected)
        _assert_clean(srv)

    def test_admit_oom_with_spec_decode_enabled(self, stack):
        # satellite: the admission failure path must also be exception-
        # safe when speculative decoding is configured
        _, _, engine = stack
        rng = np.random.default_rng(9)
        prompts = _prompts(rng, 3)
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                            spec_decode={"drafter": "ngram", "k": 4},
                            fault_injector=FaultInjector(
                                seed=0, schedule={"admit_oom": [1]}))
        reqs = [srv.submit(p, max_new_tokens=4) for p in prompts]
        with pytest.raises(InjectedFault):
            srv.step()
        srv.check_invariants()
        assert all(r.state is RequestState.QUEUED for r in reqs)
        srv.run_until_drained(max_steps=200)
        for r in reqs:
            assert r.state is RequestState.FINISHED
        _assert_clean(srv)

    def test_drafter_failure_aborts_cleanly(self, stack):
        # satellite: drafter raises mid-step with spec decode enabled —
        # running requests FAIL with a reason, nothing leaks, and the
        # server keeps serving afterwards
        _, _, engine = stack
        rng = np.random.default_rng(10)
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                            spec_decode={"drafter": "ngram", "k": 4},
                            fault_injector=FaultInjector(
                                seed=0, schedule={"drafter_error": [1]}))
        reqs = [srv.submit(p, max_new_tokens=8) for p in _prompts(rng, 2)]
        with pytest.raises(InjectedFault):
            srv.run_until_drained(max_steps=50)
        srv.check_invariants()
        assert srv.pool.free_count == 2
        for r in reqs:
            assert r.state is RequestState.FAILED
            assert r.finish_reason is FinishReason.ERROR
        assert srv.stats()["failed_reasons"] == {"error": 2}
        # the server is still healthy: fresh traffic completes
        again = srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                           max_new_tokens=4)
        srv.run_until_drained(max_steps=50)
        assert again.state is RequestState.FINISHED
        _assert_clean(srv)

    def test_nan_logits_fails_only_poisoned_slot(self, stack):
        _, _, engine = stack
        rng = np.random.default_rng(11)
        prompts = _prompts(rng, 3)
        srv = ServingEngine(engine, num_slots=3, max_queue_depth=8,
                            guard_numerics=True,
                            fault_injector=FaultInjector(
                                seed=0, schedule={"nan_logits": [2]}))
        reqs = [srv.submit(p, max_new_tokens=8) for p in prompts]
        srv.run_until_drained(max_steps=100)
        failed = [r for r in reqs if r.state is RequestState.FAILED]
        ok = [r for r in reqs if r.state is RequestState.FINISHED]
        assert len(failed) == 1 and len(ok) == 2
        assert failed[0].finish_reason is FinishReason.NUMERICAL_ERROR
        assert srv.stats()["failed_reasons"] == {"numerical_error": 1}
        # survivors are untouched by their neighbour's poisoning
        for r in ok:
            i = reqs.index(r)
            expected = engine.generate(prompts[i][None], max_new_tokens=8)[0]
            np.testing.assert_array_equal(r.tokens(), expected)
        _assert_clean(srv)

    def test_step_host_error_aborts_without_leaks(self, stack):
        _, _, engine = stack
        rng = np.random.default_rng(12)
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                            fault_injector=FaultInjector(
                                seed=0, schedule={"step_host_error": [2]}))
        reqs = [srv.submit(p, max_new_tokens=8) for p in _prompts(rng, 2)]
        with pytest.raises(InjectedFault):
            srv.run_until_drained(max_steps=50)
        srv.check_invariants()
        assert srv.pool.free_count == 2
        for r in reqs:
            assert r.state is RequestState.FAILED
            assert r.finish_reason is FinishReason.ERROR
        _assert_clean(srv)

    def test_chaos_zero_postwarmup_recompiles(self, stack):
        """End-to-end invariant: injected faults (including the NaN
        poisoning, which round-trips logits through the host) must not
        change the compiled program set, and every request still ends
        terminal with a reason."""
        _, _, engine = stack
        rng = np.random.default_rng(14)
        fi = FaultInjector(seed=0)   # empty schedule through warmup
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=16,
                            guard_numerics=True, fault_injector=fi)
        for count in (1, 2):         # cover single + batched admission
            for _ in range(count):
                srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                           max_new_tokens=3)
            srv.run_until_drained(max_steps=60)
        srv.end_warmup()
        fi.load_schedule({"nan_logits": [2], "slow_dispatch": [1]})
        reqs = [srv.submit(p, max_new_tokens=5)
                for p in _prompts(rng, 4, lo=5, hi=8)]
        guard = 0
        while srv.pending or srv.live_count:
            try:
                srv.step()
            except InjectedFault:
                pass
            guard += 1
            assert guard < 500
        assert srv.watchdog.recompiles == 0
        for r in reqs:
            assert r.state in (RequestState.FINISHED, RequestState.FAILED)
            assert r.finish_reason is not None
        _assert_clean(srv)

    def test_slow_dispatch_trips_step_wall_watchdog(self, stack):
        _, _, engine = stack
        rng = np.random.default_rng(13)
        srv = ServingEngine(engine, num_slots=2, step_wall_budget_ms=0.001,
                            fault_injector=FaultInjector(
                                seed=0, schedule={"slow_dispatch": [1]},
                                slow_ms=5.0))
        req = srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
                         max_new_tokens=2)
        srv.run_until_drained(max_steps=20)
        assert req.state is RequestState.FINISHED   # flagged, never killed
        assert srv.stats()["step_overruns"] >= 1
        _assert_clean(srv)


# ---------------------------------------------------------------------------
# stall guard
# ---------------------------------------------------------------------------
class TestStallGuard:
    def test_livelock_raises_with_dump(self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=8)
        req = srv.submit(np.arange(6, dtype=np.int32), max_new_tokens=4)
        # sever the scheduler: queued work that can never be granted is
        # exactly the livelock signature the guard exists to catch
        srv.scheduler.grant = lambda *a, **k: []
        with pytest.raises(ServingStalledError) as ei:
            srv.run_until_drained(stall_patience=5)
        dump = ei.value.dump
        assert [d["request_id"] for d in dump] == [req.request_id]
        assert dump[0]["state"] == "queued"
        assert "no progress" in str(ei.value)

    def test_max_steps_break_still_returns(self, stack):
        # the pre-existing contract: max_steps caps work WITHOUT raising
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=2)
        srv.submit(np.arange(6, dtype=np.int32), max_new_tokens=50)
        out = srv.run_until_drained(max_steps=3)
        assert isinstance(out, list)
        assert srv.live_count == 1      # genuinely mid-flight, no error
