"""End-to-end front-end tests over a REAL localhost socket: the
asyncio HTTP/1.1 + SSE server, hand-rolled client included. The
acceptance scenario: N concurrent SSE streams, one cancelled
mid-stream via DELETE, one expiring its deadline in the queue — every
timeline completes, invariants hold, no slot leaks."""

import asyncio
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import (FinishReason, ServingEngine,
                                   ServingFrontend)

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)
# compile time lands in the first TTFT; keep burn shedding out of the
# basic e2e flows (the shed path is asserted separately with the SLO
# tracker driven directly)
LENIENT_SLO = {"ttft_ms": 6e5, "gap_ms": 6e5}


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


# ---------------------------------------------------------------------------
# minimal HTTP/SSE client (stdlib asyncio streams, like the server)
# ---------------------------------------------------------------------------
def _http_bytes(method, path, body=None):
    payload = b"" if body is None else json.dumps(body).encode("utf-8")
    head = (f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n")
    return head.encode("latin-1") + payload


async def _request(port, method, path, body=None):
    """One full request/response exchange; returns (status, headers,
    body bytes). Relies on the server's Connection: close framing."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(_http_bytes(method, path, body))
    await writer.drain()
    raw = await reader.read()
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionResetError, BrokenPipeError):
        pass
    head, _, payload = raw.partition(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    status = int(lines[0].split(" ")[1])
    headers = {}
    for ln in lines[1:]:
        k, _, v = ln.partition(":")
        headers[k.strip().lower()] = v.strip()
    return status, headers, payload


async def _read_sse_head(reader):
    """Consume the HTTP response head of an SSE stream; returns status."""
    head = await reader.readuntil(b"\r\n\r\n")
    return int(head.decode("latin-1").split("\r\n")[0].split(" ")[1])


async def _next_frame(reader):
    """Parse one ``event:``/``data:`` SSE frame, or None on EOF."""
    try:
        block = await reader.readuntil(b"\n\n")
    except asyncio.IncompleteReadError:
        return None
    event, data = None, None
    for line in block.decode("utf-8").strip().split("\n"):
        if line.startswith("event: "):
            event = line[len("event: "):]
        elif line.startswith("data: "):
            data = json.loads(line[len("data: "):])
    return event, data


async def _generate(port, payload):
    """POST /v1/generate and read frames to completion. Returns the
    frame list (or the error JSON dict on a non-200 response)."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(_http_bytes("POST", "/v1/generate", payload))
    await writer.drain()
    status = await _read_sse_head(reader)
    if status != 200:
        body = await reader.read()
        writer.close()
        await writer.wait_closed()
        return status, json.loads(body) if body else {}
    frames = []
    while True:
        fr = await _next_frame(reader)
        if fr is None:
            break
        frames.append(fr)
        if fr[0] in ("done", "error"):
            break
    writer.close()
    await writer.wait_closed()
    return status, frames


def _frontend(stack, **srv_kw):
    _, _, engine = stack
    srv_kw.setdefault("num_slots", 2)
    srv = ServingEngine(engine, **srv_kw)
    return srv, ServingFrontend(srv, port=0, idle_poll_s=0.005)


def _assert_clean(srv):
    srv.check_invariants()
    assert srv.pool.free_count == srv.pool.num_slots
    assert srv.live_count == 0
    assert srv.timelines.open_ids() == []


# ---------------------------------------------------------------------------
class TestHTTP:
    def test_acceptance_concurrent_cancel_and_deadline(self, stack):
        """The ISSUE's e2e acceptance: concurrent SSE streams + one
        mid-stream DELETE + one queued deadline expiry, all timelines
        complete over a real socket."""
        srv, fe = _frontend(stack, num_slots=2, priority=True,
                            slo=LENIENT_SLO)

        async def run():
            await fe.start()
            port = fe.port
            try:
                # warm the compiled programs so stream timing is sane
                await _generate(port, {"prompt": [1, 2, 3],
                                       "max_new_tokens": 2})

                async def normal(i):
                    return await _generate(port, {
                        "prompt": [1 + i, 2, 3], "max_new_tokens": 4 + i,
                        "priority": "interactive", "tenant": f"t{i}"})

                async def cancelled():
                    reader, writer = await asyncio.open_connection(
                        "127.0.0.1", port)
                    writer.write(_http_bytes("POST", "/v1/generate", {
                        "prompt": [9, 9, 9], "max_new_tokens": 48}))
                    await writer.drain()
                    assert await _read_sse_head(reader) == 200
                    ev, data = await _next_frame(reader)
                    assert ev == "start"
                    rid = data["request_id"]
                    # one token through, then DELETE on a 2nd connection
                    await _next_frame(reader)
                    st, _, body = await _request(
                        port, "DELETE", f"/v1/requests/{rid}")
                    assert st == 200
                    frames = []
                    while True:
                        fr = await _next_frame(reader)
                        if fr is None:
                            break
                        frames.append(fr)
                        if fr[0] in ("done", "error"):
                            break
                    writer.close()
                    await writer.wait_closed()
                    return rid, frames

                async def expiring():
                    # both slots busy with the load above; 30 ms is far
                    # less than the queue wait behind 48-token decodes
                    return await _generate(port, {
                        "prompt": [5, 5, 5], "max_new_tokens": 4,
                        "deadline_ms": 30.0, "priority": "batch"})

                results = await asyncio.gather(
                    cancelled(), expiring(),
                    *[normal(i) for i in range(5)])
            finally:
                await fe.stop()
            return results

        (cancel_rid, cancel_frames), (exp_status, exp_frames), *normals = \
            asyncio.run(run())
        # 5 normal streams: start -> tokens (monotone indices) -> done
        for st, frames in normals:
            assert st == 200
            assert frames[0][0] == "start"
            toks = [d for e, d in frames if e == "token"]
            assert [t["index"] for t in toks] == list(range(len(toks)))
            assert frames[-1][0] == "done"
            assert frames[-1][1]["reason"] in ("eos", "length")
        # the DELETEd stream terminates with done/cancelled
        assert cancel_frames[-1][0] == "done"
        assert cancel_frames[-1][1]["reason"] == "cancelled"
        # the queued request expired without ever costing a slot
        assert exp_status == 200
        assert exp_frames[-1][0] == "done"
        assert exp_frames[-1][1]["reason"] == "deadline"
        _assert_clean(srv)
        tl = [e["event"] for e in srv.timeline(cancel_rid)]
        assert tl[-1] == "finished"

    def test_healthz_and_metrics(self, stack):
        srv, fe = _frontend(stack, priority=True, slo=LENIENT_SLO)

        async def run():
            await fe.start()
            try:
                h = await _request(fe.port, "GET", "/healthz")
                m = await _request(fe.port, "GET", "/metrics")
            finally:
                await fe.stop()
            return h, m

        (hst, _, hbody), (mst, mhdr, mbody) = asyncio.run(run())
        assert hst == 200
        info = json.loads(hbody)
        assert info["state"] == "healthy"
        assert info["num_slots"] == 2 and info["live_slots"] == 0
        assert set(info["class_queue_depths"]) == {"interactive",
                                                   "standard", "batch"}
        assert "class_alerts" in info and "goodput" in info
        assert mst == 200
        assert mhdr["content-type"].startswith("text/plain")
        assert b"# TYPE" in mbody or b"# HELP" in mbody

    def test_rejection_maps_to_http_error_before_stream(self, stack):
        srv, fe = _frontend(
            stack, num_slots=1, max_queue_depth=1,
            priority={"tenants": {"slow": {"tokens_per_s": 1.0,
                                           "burst_tokens": 8.0}}})

        async def run():
            await fe.start()
            port = fe.port
            try:
                # rate limit: burst 8 < prompt 3 + budget 8
                st1, body1 = await _generate(port, {
                    "prompt": [1, 2, 3], "max_new_tokens": 8,
                    "tenant": "slow"})
                # prompt too long: can never fit capacity
                st2, body2 = await _generate(port, {
                    "prompt": [1] * 60, "max_new_tokens": 32})
            finally:
                await fe.stop()
            return (st1, body1), (st2, body2)

        (st1, body1), (st2, body2) = asyncio.run(run())
        assert st1 == 429 and body1["reject_reason"] == "rate_limited"
        assert body1["retry_after_s"] > 0
        assert st2 == 400 and body2["reject_reason"] == "prompt_too_long"
        _assert_clean(srv)

    def test_client_disconnect_mid_stream_cancels_request(self, stack):
        srv, fe = _frontend(stack)

        async def run():
            await fe.start()
            port = fe.port
            try:
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port)
                writer.write(_http_bytes("POST", "/v1/generate", {
                    "prompt": [1, 2, 3], "max_new_tokens": 48}))
                await writer.drain()
                assert await _read_sse_head(reader) == 200
                ev, data = await _next_frame(reader)
                rid = data["request_id"]
                await _next_frame(reader)        # one token flowing
                writer.transport.abort()         # RST: client vanishes
                # the server notices on its next write and cancels
                for _ in range(400):
                    await asyncio.sleep(0.005)
                    done = await fe.bridge.call(
                        lambda s: s.live_count == 0
                        and s.scheduler.pending == 0)
                    if done:
                        break
            finally:
                await fe.stop()
            return rid

        rid = asyncio.run(run())
        _assert_clean(srv)
        events = srv.timeline(rid)
        assert events[-1]["event"] == "finished"
        assert events[-1]["attrs"]["reason"] == "cancelled"

    def test_malformed_requests(self, stack):
        srv, fe = _frontend(stack)

        async def run():
            await fe.start()
            port = fe.port
            try:
                results = {
                    "no_route": await _request(port, "GET", "/nope"),
                    "bad_method": await _request(port, "GET",
                                                 "/v1/generate"),
                    "bad_json": await _request(port, "POST", "/v1/generate",
                                               body=None),
                    "bad_prompt": await _request(port, "POST",
                                                 "/v1/generate",
                                                 {"prompt": "hi"}),
                    "unknown_field": await _request(
                        port, "POST", "/v1/generate",
                        {"prompt": [1], "stream": True}),
                    "bad_cancel_id": await _request(
                        port, "DELETE", "/v1/requests/xyz"),
                    "unknown_cancel": await _request(
                        port, "DELETE", "/v1/requests/424242"),
                }
            finally:
                await fe.stop()
            return results

        r = asyncio.run(run())
        assert r["no_route"][0] == 404
        assert r["bad_method"][0] == 405
        assert r["bad_json"][0] == 400
        assert r["bad_prompt"][0] == 400
        assert r["unknown_field"][0] == 400
        assert json.loads(r["unknown_field"][2])["error"].count("stream")
        assert r["bad_cancel_id"][0] == 400
        assert r["unknown_cancel"][0] == 404
        _assert_clean(srv)

    def test_zero_recompiles_after_warmup_across_http_load(self, stack):
        """The whole HTTP/bridge/priority stack must not perturb the
        engine's compiled surface: warm up, then drive mixed-class load
        over the socket and require zero post-warmup recompiles."""
        srv, fe = _frontend(stack, num_slots=2, priority=True,
                            slo=LENIENT_SLO)

        async def run():
            await fe.start()
            port = fe.port
            try:
                for i in range(3):       # warmup sweep over the buckets
                    await _generate(port, {"prompt": [1 + i, 2, 3],
                                           "max_new_tokens": 3})
                await fe.bridge.call(lambda s: s.end_warmup())
                await asyncio.gather(*[
                    _generate(port, {
                        "prompt": [i + 1, 3, 5], "max_new_tokens": 3 + i,
                        "priority": ("interactive", "standard",
                                     "batch")[i % 3]})
                    for i in range(6)])
                return await fe.bridge.call(
                    lambda s: s.watchdog.recompiles)
            finally:
                await fe.stop()

        assert asyncio.run(run()) == 0
        _assert_clean(srv)


# ---------------------------------------------------------------------------
class TestFleetFrontend:
    def test_healthz_fleet_topology_and_router_metrics(self, stack):
        """The frontend over a DISAGGREGATED fleet: ``/healthz`` carries
        the fleet block (per-role counts, transfers in flight, last
        scale event) and ``/metrics`` the router gauges — with one
        generation riding a real cross-pool page transfer end to end
        over the socket."""
        from deepspeed_tpu.serving.router import ReplicaRouter

        _, _, engine = stack

        def rep(role):
            return ServingEngine(
                engine, num_slots=2, max_queue_depth=32, prefill_chunk=8,
                paged_kv={"page_size": 8, "num_pages": None}, role=role)

        router = ReplicaRouter([rep("prefill"), rep("decode")])
        fe = ServingFrontend(router, port=0, idle_poll_s=0.005)

        async def run():
            await fe.start()
            try:
                st, frames = await _generate(fe.port, {
                    "prompt": list(range(1, 13)), "max_new_tokens": 4})
                h = await _request(fe.port, "GET", "/healthz")
                m = await _request(fe.port, "GET", "/metrics")
            finally:
                await fe.stop()
            return st, frames, h, m

        st, frames, (hst, _, hbody), (mst, mhdr, mbody) = asyncio.run(run())
        assert st == 200 and frames[0][0] == "start"
        assert frames[-1][0] == "done"
        assert len([f for f in frames if f[0] == "token"]) == 4
        assert hst == 200
        info = json.loads(hbody)
        assert info["state"] in ("healthy", "pressured")
        assert info["num_slots"] == 4 and info["live_slots"] == 0
        fleet = info["fleet"]
        assert fleet["counts"] == {"prefill": 1, "decode": 1, "both": 0}
        assert fleet["fleet_size"] == 2
        assert fleet["transfers_in_flight"] == 0
        assert fleet["transfers_total"] >= 1
        assert "last_scale_event" in fleet
        assert mst == 200
        assert mhdr["content-type"].startswith("text/plain")
        text = mbody.decode("utf-8")
        assert "router_fleet_size 2" in text
        assert "router_transfers_total" in text
        # fleet observability plane: /healthz carries the per-replica /
        # per-role summary, /metrics the merged labeled exposition
        fh = info["fleet_health"]
        assert set(fh["replicas"]) == {"0", "1"}
        assert fh["replicas"]["0"]["role"] == "prefill"
        assert set(fh["roles"]) == {"prefill", "decode"}
        assert fh["journeys"]["complete"] == fh["journeys"]["finished"]
        assert 'replica="0",role="prefill"' in text
        assert "fleet_goodput" in text
        assert "fleet_journeys_complete" in text
        router.check_invariants()
