"""Serving telemetry integration: per-request timelines must be complete
for every lifecycle outcome (finished / rejected / length_cap / failed /
requeued), traced runs must export step-phase spans plus request flow
lanes, all monitor events must share the engine's step axis, and the
recompile watchdog must read zero across warmed churn."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import RequestState, ServingEngine
from deepspeed_tpu.telemetry import RecompileAfterWarmupError, Tracer

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


class _FakeMonitor:
    enabled = True

    def __init__(self):
        self.events = []

    def write_events(self, events):
        self.events.extend(events)


def test_timeline_complete_for_finished_request(stack):
    _, _, engine = stack
    rng = np.random.default_rng(53)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8)
    req = srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                     max_new_tokens=3)
    srv.run_until_drained(max_steps=50)
    assert req.state == RequestState.FINISHED
    names = [e["event"] for e in srv.timeline(req.request_id)]
    assert names == ["submitted", "admitted", "first_token", "finished"]
    last = srv.timeline(req.request_id)[-1]
    assert last["attrs"]["reason"] == "length"
    assert last["attrs"]["new_tokens"] == 3
    assert last["attrs"]["chunks"] == 0
    assert srv.timeline(999_999) is None  # unknown id


def test_timeline_rejected_request(stack):
    _, _, engine = stack
    rng = np.random.default_rng(59)
    srv = ServingEngine(engine, num_slots=1, max_queue_depth=1)
    srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
               max_new_tokens=2)
    full = srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
                      max_new_tokens=2)
    assert full.state == RequestState.REJECTED
    tl = srv.timeline(full.request_id)
    assert [e["event"] for e in tl] == ["submitted", "rejected"]
    assert tl[-1]["attrs"]["reason"] == "queue_full"
    srv.run_until_drained(max_steps=20)


def test_timeline_length_cap(stack):
    _, _, engine = stack
    rng = np.random.default_rng(61)
    srv = ServingEngine(engine, num_slots=1, max_queue_depth=4,
                        prefill_chunk=16)
    srv.scheduler.capacity = None  # reach the engine-side safety net
    req = srv.submit(rng.integers(1, 64, size=60).astype(np.int32),
                     max_new_tokens=10)
    srv.run_until_drained(max_steps=100)
    assert req.finish_reason == "length_cap"
    names = srv.timelines.events_of(req.request_id)
    assert names[0] == "submitted" and names[-1] == "finished"
    assert "prefill_chunk" in names
    last = srv.timeline(req.request_id)[-1]
    assert last["attrs"]["reason"] == "length_cap"
    assert last["attrs"]["chunks"] == req.chunks > 0


def test_timeline_failed_request(stack):
    _, _, engine = stack
    rng = np.random.default_rng(67)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8)
    r1 = srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                    max_new_tokens=6)
    srv.step()
    assert r1.state == RequestState.RUNNING

    orig = engine._jit_decode
    engine._jit_decode = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("injected decode failure"))
    try:
        with pytest.raises(RuntimeError, match="injected"):
            srv.step()
    finally:
        engine._jit_decode = orig

    tl1 = srv.timeline(r1.request_id)
    assert tl1[-1]["event"] == "failed"
    assert tl1[-1]["attrs"]["reason"] == "error"


def test_timeline_requeued_after_admit_error(stack):
    _, _, engine = stack
    rng = np.random.default_rng(101)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8)
    req = srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                     max_new_tokens=3)

    orig = engine._jit_prefill_at
    engine._jit_prefill_at = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("injected prefill failure"))
    try:
        with pytest.raises(RuntimeError, match="injected"):
            srv.step()
    finally:
        engine._jit_prefill_at = orig

    tl = srv.timeline(req.request_id)
    assert tl[-1]["event"] == "requeued"
    assert tl[-1]["attrs"]["reason"] == "admit_error"
    srv.run_until_drained(max_steps=50)
    assert srv.timelines.events_of(req.request_id)[-1] == "finished"


def test_traced_run_exports_step_spans_and_request_lanes(stack, tmp_path):
    _, _, engine = stack
    rng = np.random.default_rng(71)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                        tracer=Tracer())
    for n, b in ((5, 3), (9, 4), (6, 2)):
        srv.submit(rng.integers(0, 64, size=n).astype(np.int32),
                   max_new_tokens=b)
    srv.run_until_drained(max_steps=50)

    path = tmp_path / "serving.json"
    srv.tracer.export(str(path))
    evs = json.loads(path.read_text())["traceEvents"]

    spans = {e["name"] for e in evs if e["ph"] == "X"}
    assert {"serving/step", "serving/grant", "serving/decode",
            "serving/sample"} <= spans
    assert "serving/admit" in spans or "serving/prefill_batch" in spans
    # per-request async lanes with begin/end pairs
    reqs = [e for e in evs if e.get("cat") == "request"]
    begins = {e["id"] for e in reqs if e["ph"] == "b"}
    ends = {e["id"] for e in reqs if e["ph"] == "e"}
    assert len(begins) == 3 and begins == ends
    # flow arrows from admission into retirement
    assert {e["ph"] for e in evs if e.get("cat") == "flow"} == {"s", "f"}
    # occupancy counter track samples
    assert any(e["ph"] == "C" and e["name"] == "serving/occupancy"
               for e in evs)
    # step spans carry the engine step id
    steps = [e["args"]["step"] for e in evs
             if e["ph"] == "X" and e["name"] == "serving/step"]
    assert steps == sorted(steps) and steps[0] >= 1


def test_set_tracer_enables_post_hoc_tracing(stack):
    _, _, engine = stack
    rng = np.random.default_rng(73)
    srv = ServingEngine(engine, num_slots=1, max_queue_depth=4)
    srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
               max_new_tokens=2)
    srv.run_until_drained(max_steps=20)
    assert srv.tracer.events_total == 0  # off by default

    tr = Tracer()
    srv.set_tracer(tr)
    assert srv.timelines.tracer is tr and srv.watchdog.tracer is tr
    srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
               max_new_tokens=2)
    srv.run_until_drained(max_steps=20)
    assert any(e["name"] == "serving/step" for e in tr.events())


def test_monitor_events_share_engine_step_axis(stack):
    _, _, engine = stack
    rng = np.random.default_rng(79)
    mon = _FakeMonitor()
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8, monitor=mon)
    for _ in range(3):
        srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                   max_new_tokens=4)
    srv.run_until_drained(max_steps=50)
    assert mon.events
    for tag, _, step in mon.events:
        assert isinstance(step, int)
        assert 0 <= step <= srv.step_id, tag
    # finish events land on the step that retired them, not a token count
    finish_steps = [s for t, _, s in mon.events
                    if t == "serving/new_tokens"]
    assert len(finish_steps) == 3
    assert max(finish_steps) <= srv.step_id


def test_publish_telemetry_routes_registry_snapshot(stack):
    _, _, engine = stack
    rng = np.random.default_rng(83)
    mon = _FakeMonitor()
    srv = ServingEngine(engine, num_slots=1, max_queue_depth=4, monitor=mon)
    srv.submit(rng.integers(0, 64, size=5).astype(np.int32),
               max_new_tokens=2)
    srv.run_until_drained(max_steps=20)
    before = len(mon.events)
    n = srv.publish_telemetry()
    assert n > 0 and len(mon.events) == before + n
    tele = [t for t, _, s in mon.events[before:]]
    assert all(t.startswith("telemetry/") for t in tele)
    assert "telemetry/serving/finished" in tele
    assert all(s == srv.step_id for _, _, s in mon.events[before:])
    # registry mirrored the counters the monitor saw
    assert srv.registry.counter("serving/finished").value == 1


def test_watchdog_zero_after_warmup_and_strict_raise(stack):
    _, _, engine = stack
    rng = np.random.default_rng(89)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=16,
                        strict_recompile=True)
    for _ in range(3):  # warm both admission buckets
        srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                   max_new_tokens=3)
    srv.run_until_drained(max_steps=50)
    srv.end_warmup()
    assert srv.watchdog.warmed

    for _ in range(5):  # churn through reused slots: no recompiles
        srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                   max_new_tokens=4)
    srv.run_until_drained(max_steps=100)
    assert srv.watchdog.recompiles == 0

    # force a fresh program: strict mode aborts at the step boundary
    srv.submit(rng.integers(0, 64, size=33).astype(np.int32),
               max_new_tokens=2)  # new prefill bucket (width 64)
    with pytest.raises(RecompileAfterWarmupError):
        srv.run_until_drained(max_steps=20)
    assert srv.watchdog.recompiles > 0
    assert srv.watchdog.summary()["recompiles"] == srv.watchdog.recompiles


def test_tracer_overhead_is_bounded(stack):
    """Tracing 50 steps of a drained server must not blow up step cost —
    a loose 2x smoke bound (the bench gates the real <2% number)."""
    import time

    _, _, engine = stack
    rng = np.random.default_rng(97)

    def run(tracer):
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=64,
                            tracer=tracer)
        for _ in range(8):
            srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
                       max_new_tokens=8)
        t0 = time.perf_counter()
        srv.run_until_drained(max_steps=200)
        return time.perf_counter() - t0

    run(None)                      # warm compile caches
    base = min(run(None), run(None))
    traced = min(run(Tracer()), run(Tracer()))
    assert traced < base * 2 + 0.05


def test_warmup_manifest_records_then_freezes(stack):
    """The watchdog's signature manifest collects every watched call's
    manifest signature during warmup, freezes at end_warmup, and
    renders in the exact grammar graftcheck's static enumeration
    emits (pinned byte-for-byte in tests/unit/analysis)."""
    _, _, engine = stack
    rng = np.random.default_rng(23)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=16)
    srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
               max_new_tokens=3)
    srv.run_until_drained(max_steps=50)
    man = srv.watchdog.signature_manifest()
    assert any(k.startswith("InferenceEngine.") for k in man)
    flat = [s for sigs in man.values() for s in sigs]
    assert flat and all(s.startswith("(") and s.endswith(")")
                        for s in flat)
    # the 6-token prompt pads to the minimum 16-wide prefill bucket
    assert any("int32[1,16]" in s
               for s in man.get("InferenceEngine._jit_prefill_at", []))

    srv.end_warmup()
    srv.submit(rng.integers(0, 64, size=6).astype(np.int32),
               max_new_tokens=3)  # same bucket: no recompile, no record
    srv.run_until_drained(max_steps=50)
    assert srv.watchdog.signature_manifest() == man  # frozen


def test_export_signatures_merges_by_union(stack, tmp_path):
    # watchdog proxies are shared per ENGINE (attach is idempotent), so
    # a merged union of distinct warmup sets needs two engines — exactly
    # the bench shape, where every arm exports into one signatures.json
    model, params, engine = stack
    rng = np.random.default_rng(29)
    path = str(tmp_path / "signatures.json")

    def serve(eng, n_tok):
        srv = ServingEngine(eng, num_slots=2, max_queue_depth=16)
        srv.submit(rng.integers(0, 64, size=n_tok).astype(np.int32),
                   max_new_tokens=2)
        srv.run_until_drained(max_steps=50)
        srv.end_warmup()
        return srv

    doc1 = serve(engine, 6).export_signatures(path)
    assert doc1["version"] == 1 and len(doc1["configs"]) == 1
    engine2 = ds.init_inference(model=model, model_parameters=params,
                                config={"dtype": "float32"})
    doc2 = serve(engine2, 20).export_signatures(
        path, merge=True, extra={"max_prompt_len": 20})
    # identical env dicts dedupe; the extra key makes this one distinct
    assert len(doc2["configs"]) == 2
    pre = doc2["programs"]["InferenceEngine._jit_prefill_at"]
    assert any("int32[1,16]" in s for s in pre)   # first engine's bucket
    assert any("int32[1,32]" in s for s in pre)   # second engine's bucket
    on_disk = json.loads(open(path).read())
    assert on_disk == doc2
