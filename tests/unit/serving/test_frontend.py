"""Async front-end unit tests: the priority scheduler (rank-ordered
grant, fair-share token slices, head-liveness, tenant rate limits and
quotas), the shared injected clock (ONE monotonic source drives
deadlines, queue expiry and rate buckets — pinned), burn-rate shedding
and preemption at the engine level, client-cancellation rollback
(mid-PREFILLING, mid-decode, paged), and the asyncio<->step-thread
bridge (streaming, cancellation, backpressure, drain-on-shutdown)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import (FIFOScheduler, FinishReason,
                                   PriorityConfig, PriorityScheduler,
                                   RejectReason, Request, RequestState,
                                   ServingEngine, TenantPolicy)
from deepspeed_tpu.serving.frontend import AsyncEngineBridge

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)

# relaxed SLO for engine tests: the first step's jit compile lands in
# TTFT, which would trip the default 500 ms target and turn burn-rate
# shedding ON mid-test (that behavior gets its own deterministic tests)
LENIENT_SLO = {"ttft_ms": 6e5, "gap_ms": 6e5}


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


class FakeClock:
    """Injected monotonic clock; tests advance ``t`` explicitly."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def _req(rid, plen=8, mnt=8, cls=None, tenant="default"):
    r = Request(rid, np.zeros(plen, np.int32), mnt)
    if cls is not None:
        r.priority_class = cls
    r.tenant = tenant
    return r


def _prompt(rng, lo=5, hi=10):
    return rng.integers(0, 64, size=int(rng.integers(lo, hi + 1))) \
              .astype(np.int32)


def _assert_clean(srv):
    srv.check_invariants()
    assert srv.pool.free_count == srv.pool.num_slots
    assert srv.live_count == 0
    assert srv.timelines.open_ids() == []


# ---------------------------------------------------------------------------
# FIFO head-liveness: the base-class guarantee the priority scheduler
# builds on (regression pin — see FIFOScheduler.grant docstring)
# ---------------------------------------------------------------------------
class TestFIFOHeadLiveness:
    def test_head_granted_over_budget_when_nothing_committed(self):
        s = FIFOScheduler(num_slots=2)
        ok, _ = s.submit(_req(0, plen=32))
        assert ok
        got = s.grant(2, 0, token_budget=4, cost=lambda r: 100)
        assert [r.request_id for r in got] == [0]

    def test_head_blocked_when_prefill_already_committed(self):
        s = FIFOScheduler(num_slots=2)
        s.submit(_req(0, plen=32))
        assert s.grant(2, 0, token_budget=4, cost=lambda r: 100,
                       spent=1) == []
        assert s.pending == 1  # still queued, granted next idle step

    def test_head_accessor_matches_pop_order(self):
        s = FIFOScheduler(num_slots=2)
        assert s.head() is None
        a, b = _req(0), _req(1)
        s.submit(a)
        s.submit(b)
        assert s.head() is a
        assert s.grant(1, 0)[0] is a
        assert s.head() is b


# ---------------------------------------------------------------------------
# priority scheduler: rank order, fair shares, liveness, page strictness
# ---------------------------------------------------------------------------
class TestPriorityGrant:
    def test_strict_rank_order_for_slots(self):
        s = PriorityScheduler(num_slots=4)
        s.submit(_req(0, cls="batch"))
        s.submit(_req(1, cls="standard"))
        s.submit(_req(2, cls="interactive"))
        got = [r.request_id for r in s.grant(2, 0)]
        assert got == [2, 1]      # rank order beats arrival order
        assert s.head().request_id == 0

    def test_head_is_oldest_of_highest_class(self):
        s = PriorityScheduler(num_slots=4)
        s.submit(_req(0, cls="batch"))
        s.submit(_req(1, cls="interactive"))
        s.submit(_req(2, cls="interactive"))
        assert s.head().request_id == 1
        assert s.head_within(0).request_id == 1
        # nothing at-or-above rank 0 once interactive drains
        s.grant(2, 0)
        assert s.head_within(0) is None
        assert s.head_within(2).request_id == 0

    def test_fair_share_slices_split_token_budget(self):
        s = PriorityScheduler(num_slots=4)
        s.submit(_req(0, cls="interactive"))
        s.submit(_req(1, cls="interactive"))
        s.submit(_req(10, cls="batch"))
        s.submit(_req(11, cls="batch"))
        # budget 20, cost 10 each, equal shares -> ONE grant per class:
        # a high-class flood cannot eat the whole step's prefill budget
        got = [r.request_id for r in
               s.grant(4, 0, token_budget=20, cost=lambda r: 10)]
        assert got == [0, 10]

    def test_shares_weight_the_split(self):
        s = PriorityScheduler(
            num_slots=4,
            priority={"classes": ("interactive", "batch"),
                      "shares": {"interactive": 3.0, "batch": 1.0}})
        for i in range(3):
            s.submit(_req(i, cls="interactive"))
        s.submit(_req(10, cls="batch"))
        s.submit(_req(11, cls="batch"))
        # budget 40 -> slices 30/10 at cost 10: three interactive, one batch
        got = [r.request_id for r in
               s.grant(5, 0, token_budget=40, cost=lambda r: 10)]
        assert got == [0, 1, 2, 10]

    def test_leftover_budget_is_work_conserving(self):
        s = PriorityScheduler(num_slots=8)
        s.submit(_req(0, cls="interactive"))
        for i in range(3):
            s.submit(_req(10 + i, cls="batch"))
        # slices 6/6; interactive spends 2, batch spends 6 in-slice and
        # the third batch request rides the global leftover (pass 2)
        cost = {0: 2, 10: 3, 11: 3, 12: 3}
        got = [r.request_id for r in
               s.grant(8, 0, token_budget=12,
                       cost=lambda r: cost[r.request_id])]
        assert got == [0, 10, 11, 12]
        assert s.pending == 0

    def test_highest_ranked_waiter_keeps_liveness_overshoot(self):
        s = PriorityScheduler(num_slots=2)
        s.submit(_req(0, cls="interactive", plen=32))
        s.submit(_req(1, cls="batch"))
        got = [r.request_id for r in
               s.grant(2, 0, token_budget=4, cost=lambda r: 100)]
        # the overshoot grants exactly the head — it must NOT also be
        # re-spent on lower classes (budget already blown)
        assert got == [0]
        assert s.pending == 1

    def test_lowest_class_progresses_when_higher_classes_idle(self):
        # satellite pin: no starvation livelock — with interactive and
        # standard idle, batch IS the highest-ranked waiter and inherits
        # the head-liveness overshoot
        s = PriorityScheduler(num_slots=2)
        s.submit(_req(0, cls="batch", plen=32))
        got = s.grant(2, 0, token_budget=1, cost=lambda r: 100)
        assert [r.request_id for r in got] == [0]

    def test_overshoot_suppressed_after_committed_work(self):
        s = PriorityScheduler(num_slots=2)
        s.submit(_req(0, cls="batch", plen=32))
        assert s.grant(2, 0, token_budget=1, cost=lambda r: 100,
                       spent=1) == []

    def test_page_budget_strict_and_global(self):
        s = PriorityScheduler(num_slots=4)
        s.submit(_req(0, cls="interactive"))
        s.submit(_req(1, cls="batch"))
        pages = {0: 5, 1: 1}
        # the interactive head does not fit 2 pages -> the WHOLE grant
        # stops; letting batch take pages the blocked head needs would
        # invert priority under memory pressure
        assert s.grant(4, 0, page_budget=2,
                       page_cost=lambda r: pages[r.request_id]) == []
        assert s.pending == 2

    def test_gang_policy_still_respected(self):
        s = PriorityScheduler(num_slots=2, policy="gang")
        s.submit(_req(0, cls="interactive"))
        assert s.grant(2, live_slots=1) == []
        assert [r.request_id for r in s.grant(2, live_slots=0)] == [0]

    def test_base_requeue_and_expire_paths_still_work(self):
        clock = FakeClock()
        s = PriorityScheduler(num_slots=2, clock=clock)
        a = _req(0, cls="batch")
        b = _req(1, cls="interactive")
        s.submit(a)
        s.submit(b)
        s.requeue_front([_req(2, cls="standard")])
        assert [r.request_id for r in s.queue] == [2, 0, 1]
        a.deadline_time = clock.t - 1.0
        expired = s.expire(clock.t)
        assert [r.request_id for r in expired] == [0]
        assert s.pending == 2


class TestPriorityAdmission:
    def test_unknown_class_fails_loudly(self):
        s = PriorityScheduler(num_slots=2)
        with pytest.raises(ValueError, match="unknown priority class"):
            s.submit(_req(0, cls="platinum"))

    def test_default_class_is_lowest_and_stamped(self):
        s = PriorityScheduler(num_slots=2)
        r = _req(0)                       # dataclass default "default"
        ok, _ = s.submit(r)
        assert ok and r.priority_class == "batch"
        assert PriorityConfig().default_class == "batch"

    def test_class_depths(self):
        s = PriorityScheduler(num_slots=2)
        s.submit(_req(0, cls="interactive"))
        s.submit(_req(1, cls="batch"))
        s.submit(_req(2, cls="batch"))
        assert s.class_depths() == {"interactive": 1, "standard": 0,
                                    "batch": 2}

    def test_tenant_rate_limit_rejects_then_refills_on_clock(self):
        clock = FakeClock()
        s = PriorityScheduler(
            num_slots=2, clock=clock,
            priority={"tenants": {"t1": {"tokens_per_s": 10.0,
                                         "burst_tokens": 20.0}}})
        # cost = prompt + max_new_tokens = 20 = exactly the burst
        ok, _ = s.submit(_req(0, plen=10, mnt=10, tenant="t1"))
        assert ok
        r = _req(1, plen=10, mnt=10, tenant="t1")
        ok, reason = s.submit(r)
        assert (ok, reason) == (False, RejectReason.RATE_LIMITED)
        assert r.retry_after_s == pytest.approx(2.0)  # 20 tokens @ 10/s
        clock.t += 2.0                    # refill WITHOUT wall time passing
        ok, _ = s.submit(_req(2, plen=10, mnt=10, tenant="t1"))
        assert ok

    def test_rate_bucket_refunded_on_downstream_rejection(self):
        clock = FakeClock()
        s = PriorityScheduler(
            num_slots=2, max_queue_depth=1, clock=clock,
            priority={"tenants": {"*": {"tokens_per_s": 10.0,
                                        "burst_tokens": 40.0}}})
        assert s.submit(_req(0, plen=10, mnt=10))[0]      # bucket 40 -> 20
        ok, reason = s.submit(_req(1, plen=10, mnt=10))   # queue full
        assert (ok, reason) == (False, RejectReason.QUEUE_FULL)
        # the rejection refunded its 20 tokens: draining the queue
        # re-admits immediately — only requests that actually joined the
        # queue consume rate (without the refund the bucket would be
        # empty here and this would be RATE_LIMITED)
        s.grant(2, 0)
        assert s.submit(_req(2, plen=10, mnt=10))[0]

    def test_tenant_queue_quota(self):
        s = PriorityScheduler(
            num_slots=2,
            priority={"tenants": {"noisy": {"max_queued": 1}}})
        assert s.submit(_req(0, tenant="noisy"))[0]
        ok, reason = s.submit(_req(1, tenant="noisy"))
        assert (ok, reason) == (False, RejectReason.TENANT_QUOTA)
        assert s.submit(_req(2, tenant="quiet"))[0]   # others unaffected

    def test_wildcard_policy_applies_to_unlisted_tenants(self):
        s = PriorityScheduler(
            num_slots=2,
            priority={"tenants": {"*": {"max_queued": 1},
                                  "vip": {"max_queued": 8}}})
        assert s.submit(_req(0, tenant="anon"))[0]
        assert s.submit(_req(1, tenant="anon"))[1] is \
            RejectReason.TENANT_QUOTA
        assert s.submit(_req(2, tenant="vip"))[0]
        assert s.submit(_req(3, tenant="vip"))[0]

    def test_config_validation(self):
        with pytest.raises(ValueError, match="duplicate"):
            PriorityConfig(classes=("a", "a"))
        with pytest.raises(ValueError, match="unknown class"):
            PriorityConfig(classes=("a",), shares={"b": 1.0})
        with pytest.raises(ValueError, match="default_class"):
            PriorityConfig(classes=("a",), default_class="z")
        with pytest.raises(ValueError, match="positive"):
            TenantPolicy(tokens_per_s=-1.0)
        assert TenantPolicy(tokens_per_s=5.0).burst_tokens == 20.0


# ---------------------------------------------------------------------------
# shared clock (satellite): ONE injected monotonic source drives
# deadlines, expiry and rate buckets together
# ---------------------------------------------------------------------------
class TestSharedClock:
    def test_clock_is_plumbed_to_scheduler_and_deadlines(self, stack):
        _, _, engine = stack
        clock = FakeClock()
        srv = ServingEngine(engine, num_slots=2, priority=True, clock=clock)
        assert srv._now is clock
        assert srv.scheduler.clock is srv._now   # same object, by identity

    def test_fake_clock_drives_deadline_expiry_without_wall_time(self, stack):
        _, _, engine = stack
        clock = FakeClock()
        srv = ServingEngine(engine, num_slots=1, priority=True, clock=clock)
        rng = np.random.default_rng(0)
        blocker = srv.submit(_prompt(rng), max_new_tokens=4)
        waiter = srv.submit(_prompt(rng), max_new_tokens=4,
                            deadline_ms=100.0)
        assert waiter.deadline_time == pytest.approx(clock.t + 0.1)
        clock.t += 1.0        # no wall time passed; only the fake clock
        srv.step()
        assert waiter.finish_reason is FinishReason.DEADLINE
        srv.run_until_drained()
        assert blocker.finish_reason is not None
        _assert_clean(srv)

    def test_fake_clock_drives_rate_bucket_through_engine(self, stack):
        _, _, engine = stack
        clock = FakeClock()
        srv = ServingEngine(
            engine, num_slots=2, clock=clock,
            priority={"tenants": {"t": {"tokens_per_s": 8.0,
                                        "burst_tokens": 16.0}}})
        rng = np.random.default_rng(1)
        p = rng.integers(0, 64, size=8).astype(np.int32)
        assert srv.submit(p, max_new_tokens=8, tenant="t").reject_reason \
            is None
        r = srv.submit(p, max_new_tokens=8, tenant="t")
        assert r.reject_reason is RejectReason.RATE_LIMITED
        assert r.retry_after_s == pytest.approx(2.0)
        clock.t += 2.0
        assert srv.submit(p, max_new_tokens=8, tenant="t").reject_reason \
            is None
        srv.run_until_drained()
        _assert_clean(srv)


# ---------------------------------------------------------------------------
# burn-rate shedding / preemption at the engine level
# ---------------------------------------------------------------------------
class TestBurnRateControl:
    def _burn(self, srv, cls="interactive"):
        """Blow one admitted request's TTFT target so the class's burn
        hits page on both horizons (goodput 0 in every window)."""
        srv.slo.observe_admitted(cls=cls)
        srv.slo.observe_finish(ttft_s=999.0, cls=cls)
        srv.slo._recompute_alert()
        assert srv.slo.class_alerts[cls] == "page"

    def test_lower_classes_shed_while_higher_class_burns(self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=2, priority=True, slo=True)
        self._burn(srv, "interactive")
        rng = np.random.default_rng(2)
        shed = srv.submit(_prompt(rng), max_new_tokens=4, priority="batch")
        assert shed.reject_reason is RejectReason.RETRY_AFTER
        assert shed.retry_after_s is not None
        # the burning class itself (and anything above the floor) is NOT
        # shed — shedding defends it, it must keep being admitted
        kept = srv.submit(_prompt(rng), max_new_tokens=4,
                          priority="interactive")
        assert kept.reject_reason is None
        srv.run_until_drained()
        _assert_clean(srv)

    def test_burn_preempts_shed_class_resident_for_protected_head(
            self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=2, priority=True, slo=True,
                            preempt_min_run_steps=0)
        rng = np.random.default_rng(3)
        b1 = srv.submit(_prompt(rng), max_new_tokens=24, priority="batch")
        b2 = srv.submit(_prompt(rng), max_new_tokens=24, priority="batch")
        srv.step()                      # both batch requests seated
        assert srv.pool.free_count == 0
        self._burn(srv, "interactive")
        vip = srv.submit(_prompt(rng), max_new_tokens=4,
                         priority="interactive")
        srv.step()
        # one shed-class resident evicted (paced: exactly one) and the
        # protected head seated in its place
        assert (b1.preemptions + b2.preemptions) == 1
        assert vip.slot is not None or vip.finish_reason is not None
        srv.run_until_drained()
        assert vip.finish_reason in (FinishReason.EOS, FinishReason.LENGTH)
        _assert_clean(srv)

    def test_no_burn_no_shed(self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=2, priority=True,
                            slo=LENIENT_SLO)
        rng = np.random.default_rng(4)
        r = srv.submit(_prompt(rng), max_new_tokens=4, priority="batch")
        assert r.reject_reason is None
        assert srv._shed_floor() is None
        srv.run_until_drained()
        _assert_clean(srv)

    def test_priority_kw_requires_priority_engine(self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=2)
        with pytest.raises(ValueError, match="priority-enabled"):
            srv.submit(np.zeros(4, np.int32), priority="interactive")


# ---------------------------------------------------------------------------
# cancellation rollback (client disconnect / DELETE): queued,
# mid-PREFILLING, mid-decode, paged — no slot or page leaks
# ---------------------------------------------------------------------------
class TestCancellation:
    def test_cancel_queued_request_never_costs_a_prefill(self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=1)
        rng = np.random.default_rng(5)
        blocker = srv.submit(_prompt(rng), max_new_tokens=4)
        waiter = srv.submit(_prompt(rng), max_new_tokens=4)
        got = srv.cancel(waiter.request_id)
        assert got is waiter
        assert waiter.finish_reason is FinishReason.CANCELLED
        assert waiter.admit_time is None      # never seated
        srv.run_until_drained()
        assert blocker.finish_reason is not None
        _assert_clean(srv)
        tl = [e["event"] for e in srv.timeline(waiter.request_id)]
        assert tl[0] == "submitted" and tl[-1] == "finished"

    def test_cancel_mid_prefilling_releases_slot(self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=2, prefill_chunk=4,
                            prefill_token_budget=4)
        rng = np.random.default_rng(6)
        prompt = rng.integers(0, 64, size=14).astype(np.int32)
        r = srv.submit(prompt, max_new_tokens=4)
        srv.step()
        assert r.state is RequestState.PREFILLING   # chunks remain
        got = srv.cancel(r.request_id)
        assert got is r and r.finish_reason is FinishReason.CANCELLED
        assert not srv._prefill_queue               # chunk queue filtered
        srv.step()                                  # engine keeps running
        _assert_clean(srv)

    def test_cancel_mid_decode_releases_slot(self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=2)
        rng = np.random.default_rng(7)
        r = srv.submit(_prompt(rng), max_new_tokens=32)
        survivor = srv.submit(_prompt(rng), max_new_tokens=8)
        srv.step()
        srv.step()
        assert r.state is RequestState.RUNNING and r.output_tokens
        n = len(r.output_tokens)
        assert srv.cancel(r.request_id) is r
        assert r.finish_reason is FinishReason.CANCELLED
        assert len(r.output_tokens) == n        # nothing generated after
        srv.run_until_drained()
        assert survivor.finish_reason in (FinishReason.EOS,
                                          FinishReason.LENGTH)
        _assert_clean(srv)

    def test_cancel_mid_decode_paged_frees_pages(self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=2, prefill_chunk=8,
                            paged_kv={"page_size": 8,
                                      "prefix_cache": False})
        rng = np.random.default_rng(8)
        r = srv.submit(_prompt(rng), max_new_tokens=24)
        srv.step()
        srv.step()
        assert srv.pool.free_page_count < srv.pool.num_pages
        assert srv.cancel(r.request_id) is r
        assert srv.pool.free_page_count == srv.pool.num_pages
        _assert_clean(srv)

    def test_cancel_unknown_or_terminal_returns_none(self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=1)
        rng = np.random.default_rng(9)
        r = srv.submit(_prompt(rng), max_new_tokens=2)
        srv.run_until_drained()
        assert srv.cancel(r.request_id) is None     # races the final token
        assert srv.cancel(10_000) is None
        _assert_clean(srv)

    def test_cancel_withdraws_slo_admission(self, stack):
        _, _, engine = stack
        srv = ServingEngine(engine, num_slots=2, priority=True,
                            slo=LENIENT_SLO)
        rng = np.random.default_rng(10)
        srv.submit(_prompt(rng), max_new_tokens=16)
        r2 = srv.submit(_prompt(rng), max_new_tokens=16)
        srv.step()
        srv.cancel(r2.request_id)
        assert srv.slo.cancelled_total == 1
        srv.run_until_drained()
        # the cancelled request neither helps nor hurts goodput
        assert srv.slo.goodput() == pytest.approx(1.0)
        _assert_clean(srv)


# ---------------------------------------------------------------------------
# asyncio <-> step-thread bridge
# ---------------------------------------------------------------------------
async def _collect(stream):
    return [ev async for ev in stream]


class TestBridge:
    def _srv(self, stack, **kw):
        _, _, engine = stack
        kw.setdefault("num_slots", 2)
        return ServingEngine(engine, **kw)

    def test_submit_streams_tokens_then_done(self, stack):
        srv = self._srv(stack)

        async def run():
            bridge = AsyncEngineBridge(srv, idle_poll_s=0.005)
            await bridge.start()
            try:
                req, stream = await bridge.submit(
                    [1, 2, 3, 4], max_new_tokens=5)
                events = await _collect(stream)
            finally:
                await bridge.stop()
            return req, events

        req, events = asyncio.run(run())
        tokens = [e for e in events if e["event"] == "token"]
        assert [e["index"] for e in tokens] == list(range(len(tokens)))
        assert [e["token"] for e in tokens] == req.output_tokens
        assert events[-1]["event"] == "done"
        assert events[-1]["reason"] in ("eos", "length")
        assert events[-1]["tokens"] == len(req.output_tokens)
        _assert_clean(srv)

    def test_concurrent_streams_all_complete(self, stack):
        srv = self._srv(stack)

        async def run():
            bridge = AsyncEngineBridge(srv, idle_poll_s=0.005)
            await bridge.start()
            try:
                pairs = [await bridge.submit([1 + i, 2, 3],
                                             max_new_tokens=4 + i)
                         for i in range(5)]
                results = await asyncio.gather(
                    *[_collect(s) for _, s in pairs])
            finally:
                await bridge.stop()
            return pairs, results

        pairs, results = asyncio.run(run())
        for (req, _), events in zip(pairs, results):
            assert events[-1]["event"] == "done"
            assert events[-1]["request_id"] == req.request_id
        _assert_clean(srv)

    def test_cancel_mid_stream_emits_terminal_cancelled(self, stack):
        srv = self._srv(stack)

        async def run():
            bridge = AsyncEngineBridge(srv, idle_poll_s=0.005)
            await bridge.start()
            try:
                req, stream = await bridge.submit([1, 2, 3],
                                                  max_new_tokens=48)
                first = await stream.__anext__()     # at least one token
                assert await bridge.cancel(req.request_id) is True
                rest = await _collect(stream)
            finally:
                await bridge.stop()
            return first, rest

        first, rest = asyncio.run(run())
        assert first["event"] == "token"
        assert rest[-1]["event"] == "done"
        assert rest[-1]["reason"] == "cancelled"
        _assert_clean(srv)

    def test_cancel_unknown_id_returns_false(self, stack):
        srv = self._srv(stack)

        async def run():
            bridge = AsyncEngineBridge(srv, idle_poll_s=0.005)
            await bridge.start()
            try:
                return await bridge.cancel(31337)
            finally:
                await bridge.stop()

        assert asyncio.run(run()) is False

    def test_rejected_submit_yields_single_terminal_event(self, stack):
        srv = self._srv(stack, num_slots=1, max_queue_depth=1)

        async def run():
            bridge = AsyncEngineBridge(srv, idle_poll_s=0.005)
            await bridge.start()
            try:
                # fill slot + queue, then overflow
                await bridge.submit([1, 2], max_new_tokens=16)
                await bridge.submit([1, 2], max_new_tokens=16)
                req, stream = await bridge.submit([1, 2], max_new_tokens=4)
                events = await _collect(stream)
            finally:
                await bridge.stop()
            return req, events

        req, events = asyncio.run(run())
        assert req.state is RequestState.REJECTED
        assert len(events) == 1
        assert events[0]["reason"] == "rejected"
        assert events[0]["reject_reason"] == "queue_full"
        _assert_clean(srv)

    def test_slow_consumer_is_closed_and_cancelled(self, stack):
        srv = self._srv(stack)

        async def run():
            bridge = AsyncEngineBridge(srv, stream_buffer=2,
                                       idle_poll_s=0.005)
            await bridge.start()
            try:
                req, stream = await bridge.submit([1, 2, 3],
                                                  max_new_tokens=48)
                for _ in range(400):        # deaf consumer: never reads
                    await asyncio.sleep(0.005)
                    if stream.closed and not bridge._streams:
                        break
                ev = await stream.__anext__()
                with pytest.raises(StopAsyncIteration):
                    await stream.__anext__()
            finally:
                await bridge.stop()
            return req, ev

        req, ev = asyncio.run(run())
        assert ev == {"event": "error", "reason": "slow_consumer",
                      "request_id": req.request_id}
        assert req.finish_reason is FinishReason.CANCELLED
        _assert_clean(srv)

    def test_call_serializes_reads_onto_step_thread(self, stack):
        srv = self._srv(stack)

        async def run():
            bridge = AsyncEngineBridge(srv, idle_poll_s=0.005)
            await bridge.start()
            try:
                req, stream = await bridge.submit([1, 2], max_new_tokens=4)
                stats = await bridge.call(lambda s: s.stats())
                await _collect(stream)
            finally:
                await bridge.stop()
            return stats

        stats = asyncio.run(run())
        assert isinstance(stats, dict) and "completed" in stats

    def test_stop_drains_in_flight_requests(self, stack):
        srv = self._srv(stack)

        async def run():
            bridge = AsyncEngineBridge(srv, idle_poll_s=0.005)
            await bridge.start()
            req, stream = await bridge.submit([1, 2, 3], max_new_tokens=8)
            await bridge.stop(drain=True)      # no reads before stop
            return req, await _collect(stream)

        req, events = asyncio.run(run())
        assert req.finish_reason in (FinishReason.EOS, FinishReason.LENGTH)
        assert events[-1]["event"] == "done"
        _assert_clean(srv)

    def test_stop_without_drain_closes_streams(self, stack):
        srv = self._srv(stack)

        async def run():
            bridge = AsyncEngineBridge(srv, idle_poll_s=0.005)
            await bridge.start()
            req, stream = await bridge.submit([1, 2, 3],
                                              max_new_tokens=48)
            await stream.__anext__()
            await bridge.stop(drain=False)
            return req, await _collect(stream)

        req, events = asyncio.run(run())
        assert events[-1]["event"] == "done"
        assert events[-1]["reason"] == "shutdown"
        # not drained: the engine-side request may be unfinished, but the
        # bridge must not be left running
        srv.check_invariants()

    def test_submit_kwargs_validation_error_propagates(self, stack):
        srv = self._srv(stack)

        async def run():
            bridge = AsyncEngineBridge(srv, idle_poll_s=0.005)
            await bridge.start()
            try:
                with pytest.raises(ValueError, match="max_new_tokens"):
                    await bridge.submit([1, 2], max_new_tokens=0)
            finally:
                await bridge.stop()

        asyncio.run(run())
        _assert_clean(srv)

    def test_stream_buffer_floor(self, stack):
        srv = self._srv(stack)
        with pytest.raises(ValueError, match="stream_buffer"):
            AsyncEngineBridge(srv, stream_buffer=1)

    def test_concurrent_calls_racing_stop_never_hang(self, stack):
        """Stress the shutdown race: call() coroutines hammer the op
        queue while stop(drain=True) runs. Before the bridge rejected
        leftover ops, a call enqueued between the step thread's final
        queue drain and its exit awaited its future forever; now every
        racing call must either return a value or raise RuntimeError —
        a hang fails the gather timeout below."""
        srv = self._srv(stack)

        async def one_round(bridge):
            await bridge.start()
            outcomes = {"ok": 0, "rejected": 0}

            async def hammer():
                while True:
                    try:
                        n = await bridge.call(lambda s: s.live_count)
                    except RuntimeError:
                        outcomes["rejected"] += 1
                        return
                    assert n == 0
                    outcomes["ok"] += 1

            tasks = [asyncio.ensure_future(hammer()) for _ in range(6)]
            await asyncio.sleep(0.01)         # let the hammering overlap
            await bridge.stop(drain=True)
            await asyncio.wait_for(asyncio.gather(*tasks), timeout=30)
            assert outcomes["rejected"] == 6  # every task exited cleanly
            assert bridge._ops.empty()        # nothing left un-serviced
            with pytest.raises(RuntimeError, match="not running"):
                await bridge.call(lambda s: 0)
            return outcomes["ok"]

        async def run():
            bridge = AsyncEngineBridge(srv, idle_poll_s=0.002)
            total_ok = 0
            for _ in range(10):               # re-roll the race window
                total_ok += await one_round(bridge)
            return total_ok

        total_ok = asyncio.run(run())
        assert total_ok > 0                   # the calls really ran
        _assert_clean(srv)

    def test_ops_left_after_thread_exit_are_rejected(self, stack):
        """Deterministic pin for the leftover-op path: an op sitting in
        the queue once the step thread is gone must have its future
        rejected fast (never resolved, never hung)."""
        srv = self._srv(stack)

        async def run():
            bridge = AsyncEngineBridge(srv, idle_poll_s=0.002)
            await bridge.start()
            await bridge.stop(drain=False)
            # simulate the racing op that slipped past the final drain
            fut = asyncio.get_running_loop().create_future()
            bridge._ops.put(("call", (lambda s: 0), None, fut))
            bridge._reject_pending_ops("stopped")
            with pytest.raises(RuntimeError, match="not serviced"):
                await fut
            assert bridge._ops.empty()

        asyncio.run(run())
        _assert_clean(srv)
