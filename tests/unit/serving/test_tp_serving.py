"""Sharded serving tests (the tentpole invariants): serving over a
``(data, model)`` mesh is a pure PLACEMENT change — TP=1 greedy outputs
are bitwise identical to the single-chip engine (pinned, not
approximately equal), TP=2 greedy outputs equal TP=1 exactly on the
forced-host-device CPU mesh, and neither mesh shape recompiles any
jitted serving entry after warmup (verified with the ARMED strict
watchdog — an unarmed watchdog makes a zero count vacuous)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import RequestState, ServingEngine

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)
SLOTS = 4


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    return model, params


def _workload(seed=17, n=8):
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, 64, size=int(rng.integers(5, 13)))
               .astype(np.int32) for _ in range(n)]
    budgets = [int(rng.integers(4, 9)) for _ in range(n)]
    return prompts, budgets


def _serve(srv, prompts, budgets):
    """Warm every admission group size (staggered retirements admit
    singletons mid-decode, not just full batches) -> arm the watchdog
    -> measured wave. Any post-warmup recompile raises
    RecompileAfterWarmupError at the step boundary because the server
    runs strict."""
    for count in range(1, SLOTS + 1):
        for p in prompts[:count]:
            srv.submit(p, max_new_tokens=2)
        srv.run_until_drained(max_steps=400)
    for p in prompts:
        srv.submit(p, max_new_tokens=2)
    srv.run_until_drained(max_steps=400)
    srv.end_warmup()
    reqs = [srv.submit(p, max_new_tokens=b)
            for p, b in zip(prompts, budgets)]
    srv.run_until_drained(max_steps=400)
    for r in reqs:
        assert r.state == RequestState.FINISHED
    return [list(r.output_tokens) for r in reqs]


def _tp_server(model, params, tp_mesh, data, model_ax):
    mesh = tp_mesh(data=data, model=model_ax)
    engine = ds.init_inference(model, model_parameters=params,
                               dtype="fp32", mesh=mesh)
    return ServingEngine(engine, num_slots=SLOTS, max_queue_depth=32,
                         strict_recompile=True)


def test_tp1_serving_bitwise_matches_single_chip(model_and_params,
                                                 tp_mesh):
    """TP=1 (model axis size 1): the axis-rules table normalizes every
    model-axis rule away, so committed placements are identical to
    single-chip and outputs must be BITWISE equal to ``generate()``."""
    model, params = model_and_params
    prompts, budgets = _workload()
    single = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    expected = [list(single.generate(p[None], max_new_tokens=b)[0]
                     [len(p):]) for p, b in zip(prompts, budgets)]

    srv = _tp_server(model, params, tp_mesh, data=8, model_ax=1)
    got = _serve(srv, prompts, budgets)
    assert got == expected
    assert srv.watchdog.recompiles == 0
    srv.check_invariants()


def test_tp2_serving_matches_tp1_exact(model_and_params, tp_mesh):
    """TP=2 on the forced-host CPU mesh: greedy outputs equal TP=1
    exactly (CPU collectives are deterministic), and the sharded mesh
    does not fork any executable after warmup — the recompile-free
    tentpole invariant, enforced by the strict watchdog."""
    model, params = model_and_params
    prompts, budgets = _workload(seed=29)

    srv1 = _tp_server(model, params, tp_mesh, data=8, model_ax=1)
    out1 = _serve(srv1, prompts, budgets)

    srv2 = _tp_server(model, params, tp_mesh, data=4, model_ax=2)
    # slots=4 shard over data=4 here: the slot-sharded decode path
    assert srv2.engine.mesh.shape["model"] == 2
    out2 = _serve(srv2, prompts, budgets)

    assert out2 == out1
    assert srv1.watchdog.recompiles == 0
    assert srv2.watchdog.recompiles == 0
    srv1.check_invariants()
    srv2.check_invariants()


def test_tp2_paged_serving_matches_dense(model_and_params, tp_mesh):
    """Paged KV on the TP=2 mesh: same outputs as the dense slot pool
    on the same mesh — paging and sharding compose without changing
    tokens or recompiling."""
    model, params = model_and_params
    prompts, budgets = _workload(seed=41)

    dense = _tp_server(model, params, tp_mesh, data=4, model_ax=2)
    out_dense = _serve(dense, prompts, budgets)

    mesh = tp_mesh(data=4, model=2)
    engine = ds.init_inference(model, model_parameters=params,
                               dtype="fp32", mesh=mesh)
    paged = ServingEngine(engine, num_slots=SLOTS, max_queue_depth=32,
                          prefill_chunk=8, strict_recompile=True,
                          paged_kv={"page_size": 8, "num_pages": 48})
    out_paged = _serve(paged, prompts, budgets)

    assert out_paged == out_dense
    assert paged.watchdog.recompiles == 0
    paged.check_invariants()
