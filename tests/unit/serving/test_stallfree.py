"""Stall-free admission tests: chunked prefill interleaved with decode
and batched bucketed admission must be pure SCHEDULING changes — greedy
tokens bitwise-match ``generate()`` through every admission path, the
chunk/batch programs never recompile on churn, long prompts stop
stalling live decode slots, and capacity exhaustion retires with
``"length_cap"`` instead of silently clamping cache writes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import RequestState, ServingEngine

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


def _prompts(rng, lengths):
    return [rng.integers(1, 64, size=n).astype(np.int32) for n in lengths]


def test_chunked_prefill_parity_with_generate(stack):
    """Prompts longer than the chunk width stream in chunk by chunk; the
    resulting greedy tokens must bitwise-match whole-prompt generate()."""
    _, _, engine = stack
    rng = np.random.default_rng(23)
    lengths = [40, 33, 17]          # 3 chunks, 3 chunks (odd tail), 2 chunks
    budgets = [6, 5, 4]
    prompts = _prompts(rng, lengths)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                        prefill_chunk=16)
    assert srv._stall_free and srv.prefill_chunk == 16
    reqs = [srv.submit(p, max_new_tokens=b) for p, b in zip(prompts, budgets)]
    srv.run_until_drained(max_steps=300)
    for req, prompt, budget in zip(reqs, prompts, budgets):
        assert req.state == RequestState.FINISHED, req.request_id
        expected = engine.generate(prompt[None], max_new_tokens=budget)[0]
        np.testing.assert_array_equal(req.tokens(), expected,
                                      err_msg=f"req {req.request_id}")


def test_bucket_boundary_prompt_lengths(stack):
    """Power-of-two bucket edges (15/16/17, 31/32/33) and a prompt that
    exactly fills its slot with its budget (60 + 4 = capacity 64) must
    all admit, finish, and match generate() bitwise."""
    _, _, engine = stack
    rng = np.random.default_rng(29)
    lengths = [15, 16, 17, 31, 32, 33, 60]
    budgets = [3, 3, 3, 3, 3, 3, 4]
    prompts = _prompts(rng, lengths)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                        prefill_chunk=16)
    reqs = [srv.submit(p, max_new_tokens=b) for p, b in zip(prompts, budgets)]
    srv.run_until_drained(max_steps=400)
    for req, prompt, budget in zip(reqs, prompts, budgets):
        assert req.state == RequestState.FINISHED
        assert req.finish_reason == "length"
        expected = engine.generate(prompt[None], max_new_tokens=budget)[0]
        np.testing.assert_array_equal(req.tokens(), expected,
                                      err_msg=f"len {req.prompt_len}")


def test_long_prompt_does_not_stall_running_slot(stack):
    """THE stall-free property: while a long prompt is PREFILLING chunk
    by chunk, an already-running request keeps emitting one token per
    step — admission no longer monopolizes whole steps."""
    _, _, engine = stack
    rng = np.random.default_rng(31)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                        prefill_chunk=16)
    short = srv.submit(rng.integers(1, 64, size=6).astype(np.int32),
                       max_new_tokens=20)
    srv.step()
    assert short.state == RequestState.RUNNING

    long = srv.submit(rng.integers(1, 64, size=48).astype(np.int32),
                      max_new_tokens=4)
    while long.state in (RequestState.QUEUED, RequestState.PREFILLING):
        before = len(short.output_tokens)
        srv.step()
        if long.state == RequestState.PREFILLING:
            # a mid-prefill step still ran the decode for the live slot
            assert len(short.output_tokens) == before + 1
    assert long.state == RequestState.RUNNING
    assert long.prefill_pos == long.prompt_len
    srv.run_until_drained(max_steps=100)
    for req in (short, long):
        expected = engine.generate(np.asarray(req.prompt)[None],
                                   max_new_tokens=req.max_new_tokens)[0]
        np.testing.assert_array_equal(req.tokens(), expected)
    # the long admission took multiple steps => multiple prefill
    # dispatches, and decode time kept accumulating alongside
    s = srv.stats()
    assert s["prefill_dispatches"] >= 3
    assert s["stall_time_s"] > 0 and s["decode_time_s"] > 0


class _FakeMonitor:
    enabled = True

    def __init__(self):
        self.events = []

    def write_events(self, events):
        self.events.extend(events)


def test_length_cap_retires_full_slot(stack):
    """A slot whose cache row fills to max_seq_len retires with
    ``"length_cap"`` (plus its monitor event) instead of silently
    clamp-overwriting the last column forever."""
    _, _, engine = stack
    rng = np.random.default_rng(37)
    mon = _FakeMonitor()
    srv = ServingEngine(engine, num_slots=1, max_queue_depth=4,
                        prefill_chunk=16, monitor=mon)
    # normal admission control forbids prompt+budget > capacity, which is
    # exactly what makes the cap unreachable; disable it to exercise the
    # engine-side safety net behind it
    srv.scheduler.capacity = None
    req = srv.submit(rng.integers(1, 64, size=60).astype(np.int32),
                     max_new_tokens=10)
    srv.run_until_drained(max_steps=100)
    assert req.state == RequestState.FINISHED
    assert req.finish_reason == "length_cap"
    # 60 prompt positions + first token at 60 + 4 decode writes = 64
    assert len(req.output_tokens) == 5
    assert int(srv.pool.free_count) == 1  # slot returned
    assert "serving/finished/length_cap" in [t for t, _, _ in mon.events]


def test_spec_decode_skips_prefilling_slots(stack):
    """Speculative decoding + chunked admission: verify steps must not
    advance (or corrupt) half-prefilled rows — outputs stay bitwise
    equal to generate() for both the running and the chunked request."""
    _, _, engine = stack
    rng = np.random.default_rng(41)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                        prefill_chunk=16, spec_decode={"drafter": "ngram",
                                                       "k": 4})
    short = srv.submit(rng.integers(1, 64, size=9).astype(np.int32),
                       max_new_tokens=12)
    long = srv.submit(rng.integers(1, 64, size=44).astype(np.int32),
                      max_new_tokens=6)
    srv.run_until_drained(max_steps=200)
    for req in (short, long):
        assert req.state == RequestState.FINISHED
        expected = engine.generate(np.asarray(req.prompt)[None],
                                   max_new_tokens=req.max_new_tokens)[0]
        np.testing.assert_array_equal(req.tokens(), expected,
                                      err_msg=f"req {req.request_id}")


def test_batched_admission_is_one_dispatch(stack):
    """Same-bucket waiting prompts admit through ONE prefill dispatch and
    ONE multi-row scatter, not one dispatch per prompt."""
    _, _, engine = stack
    rng = np.random.default_rng(43)
    srv = ServingEngine(engine, num_slots=4, max_queue_depth=8,
                        prefill_chunk=16, prefill_token_budget=64)
    reqs = [srv.submit(p, max_new_tokens=3)
            for p in _prompts(rng, [5, 9, 12])]

    calls = []
    orig = engine._jit_prefill_at

    def counting(*a, **k):
        calls.append(np.shape(a[1]))
        return orig(*a, **k)

    engine._jit_prefill_at = counting
    try:
        srv.step()
    finally:
        engine._jit_prefill_at = orig
    assert len(calls) == 1          # one batched dispatch for all three
    assert calls[0][0] == 4         # power-of-two batch bucket (3 -> 4)
    assert all(r.state == RequestState.RUNNING for r in reqs)
    assert srv.stats()["prefill_dispatches"] == 1
    srv.run_until_drained(max_steps=50)
    for req in reqs:
        expected = engine.generate(np.asarray(req.prompt)[None],
                                   max_new_tokens=3)[0]
        np.testing.assert_array_equal(req.tokens(), expected)


def test_token_budget_bounds_admission(stack):
    """The per-step token budget defers admissions past the budget and an
    in-flight chunk blocks new grants entirely — but the FIFO head is
    never starved (liveness overshoot when nothing else was spent)."""
    _, _, engine = stack
    rng = np.random.default_rng(47)
    srv = ServingEngine(engine, num_slots=4, max_queue_depth=8,
                        prefill_chunk=16, prefill_token_budget=16)
    a = srv.submit(rng.integers(1, 64, size=6).astype(np.int32),
                   max_new_tokens=8)
    b = srv.submit(rng.integers(1, 64, size=6).astype(np.int32),
                   max_new_tokens=8)
    srv.step()                       # budget 16 = one bucket-16 admission
    assert a.state == RequestState.RUNNING
    assert b.state == RequestState.QUEUED
    srv.step()
    assert b.state == RequestState.RUNNING

    long = srv.submit(rng.integers(1, 64, size=40).astype(np.int32),
                      max_new_tokens=4)
    srv.step()                       # head granted despite cost==budget
    assert long.state == RequestState.PREFILLING
    c = srv.submit(rng.integers(1, 64, size=6).astype(np.int32),
                   max_new_tokens=4)
    srv.step()                       # in-flight chunk consumes the budget
    assert long.state == RequestState.PREFILLING
    assert c.state == RequestState.QUEUED
    srv.run_until_drained(max_steps=100)
    for req in (a, b, long, c):
        assert req.state == RequestState.FINISHED
        expected = engine.generate(np.asarray(req.prompt)[None],
                                   max_new_tokens=req.max_new_tokens)[0]
        np.testing.assert_array_equal(req.tokens(), expected)


def test_no_recompile_across_chunked_and_batched_churn(stack):
    """Extended churn coverage: after one warmup wave that touches every
    program (batched admission at nB=1/2, the chunk program, decode),
    further waves of NEW lengths/offsets/slots must not add a single
    compiled program."""
    _, _, engine = stack
    rng = np.random.default_rng(53)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=16,
                        prefill_chunk=16)
    # warmup: two shorts together (nB=2), a straggler short (nB=1 refill),
    # and a long prompt (chunk program at several offsets)
    for n, b in [(6, 3), (9, 3), (7, 3), (40, 3)]:
        srv.submit(rng.integers(1, 64, size=n).astype(np.int32),
                   max_new_tokens=b)
    srv.run_until_drained(max_steps=200)
    n_decode = engine._jit_decode._cache_size()
    n_prefill = engine._jit_prefill_at._cache_size()
    n_chunk = engine._jit_prefill_chunk._cache_size()
    srv.end_warmup()  # arm the watchdog's post-warmup counter

    # churn: different prompt lengths in the same buckets, different
    # chunk counts/final-tail widths, reused slots
    for n, b in [(5, 4), (11, 2), (33, 3), (48, 2), (8, 3), (17, 2)]:
        srv.submit(rng.integers(1, 64, size=n).astype(np.int32),
                   max_new_tokens=b)
    srv.run_until_drained(max_steps=400)
    assert engine._jit_decode._cache_size() == n_decode
    assert engine._jit_prefill_at._cache_size() == n_prefill
    assert engine._jit_prefill_chunk._cache_size() == n_chunk
    assert srv.watchdog.recompiles == 0


def test_config_validation_and_fallbacks(stack):
    """Knob validation: chunk auto-halves until it divides capacity,
    budget below the chunk raises, chunk=0 or gang policy falls back to
    serial admission."""
    _, _, engine = stack
    srv = ServingEngine(engine, num_slots=1, prefill_chunk=48)
    assert srv._stall_free
    assert srv.pool.capacity % srv.prefill_chunk == 0
    with pytest.raises(ValueError, match="prefill_token_budget"):
        ServingEngine(engine, num_slots=1, prefill_chunk=32,
                      prefill_token_budget=16)
    with pytest.raises(ValueError, match="prefill_chunk"):
        ServingEngine(engine, num_slots=1, prefill_chunk=-1)
    off = ServingEngine(engine, num_slots=1, prefill_chunk=0)
    assert not off._stall_free and off.prefill_token_budget is None
    gang = ServingEngine(engine, num_slots=1, policy="gang")
    assert not gang._stall_free


def test_metrics_prefill_decode_split(stack):
    _, _, engine = stack
    rng = np.random.default_rng(59)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                        prefill_chunk=16)
    for n in (6, 10, 40):
        srv.submit(rng.integers(1, 64, size=n).astype(np.int32),
                   max_new_tokens=4)
    srv.run_until_drained(max_steps=200)
    s = srv.stats()
    assert s["completed"] == 3
    assert s["prefill_tokens"] == 6 + 10 + 40  # true tokens, not padding
    assert s["prefill_dispatches"] >= 3
    assert s["prefill_time_s"] > 0 and s["decode_time_s"] > 0
    assert 0 <= s["stall_time_s"] <= s["prefill_time_s"]
    # inter-token gap tail: every step where a RUNNING request waited
    # contributes one whole-step wall time
    assert s["step_gap_p50_ms"] is not None and s["step_gap_p50_ms"] > 0
    assert s["step_gap_p99_ms"] >= s["step_gap_p50_ms"]
