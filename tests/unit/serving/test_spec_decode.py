"""Speculative decoding tests: draft–verify over the slot pool must be a
pure THROUGHPUT change — greedy tokens bitwise-match the spec-off server
(and whole-batch ``generate()``) across multi-wave staggered workloads,
slot churn still never recompiles, rollback math keeps the KV state
machine consistent through eos/budget truncation, and the config block
validates its knobs up front."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import (RequestState, ServingEngine, SlotPool,
                                   SpecDecodeConfig)
from deepspeed_tpu.serving.spec_decode import NGramDrafter, make_drafter

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


def _spec(k=4, **kw):
    return dict({"drafter": "ngram", "k": k, "max_ngram": 3}, **kw)


def _mixed_prompts(rng, n):
    """Half repetitive (drafter's home turf), half random (acceptance ~0 —
    the graceful-degradation path) — parity must hold for BOTH."""
    prompts = []
    for i in range(n):
        T = int(rng.integers(8, 28))
        if i % 2 == 0:
            motif = rng.integers(0, 64, size=int(rng.integers(3, 6)))
            prompts.append(np.tile(motif, T // len(motif) + 1)[:T]
                           .astype(np.int32))
        else:
            prompts.append(rng.integers(0, 64, size=T).astype(np.int32))
    return prompts


# ---------------------------------------------------------------- parity
def test_greedy_parity_multiwave_staggered(stack):
    """The acceptance bar: n-gram-drafted speculative decode through 2
    slots (multi-wave slot reuse) with STAGGERED arrivals emits exactly
    the tokens the spec-off server — and generate() — emits."""
    _, _, engine = stack
    rng = np.random.default_rng(23)
    prompts = _mixed_prompts(rng, 7)
    budgets = [int(b) for b in rng.integers(4, 24, size=7)]

    def run(spec):
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=16,
                            spec_decode=spec)
        reqs = []
        for p, b in zip(prompts, budgets):   # staggered: one per step
            reqs.append(srv.submit(p, max_new_tokens=b))
            srv.step()
        srv.run_until_drained(max_steps=300)
        return reqs, srv.stats()

    off, _ = run(None)
    on, s = run(_spec(k=4))
    assert all(r.state == RequestState.FINISHED for r in off + on)
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a.tokens(), b.tokens(),
                                      err_msg=f"req {a.request_id}")
    for r, p, budget in zip(on, prompts, budgets):
        expected = engine.generate(p[None], max_new_tokens=budget)[0]
        np.testing.assert_array_equal(r.tokens(), expected)
    # the repetitive half must actually speculate (else this tests nothing)
    assert s["spec_drafted"] > 0 and s["spec_accepted"] > 0
    assert s["tokens_per_decode_step"] > 1.0
    assert s["decode_steps"] < sum(budgets)  # fewer steps than tokens


def test_eos_mid_accepted_chunk(stack):
    """EOS emitted INSIDE an accepted draft chunk truncates consumption,
    retires the slot that step, and still matches generate()'s prefix."""
    _, _, engine = stack
    motif = np.array([7, 3, 11, 5], np.int32)
    prompt = np.tile(motif, 5)
    full = engine.generate(prompt[None], max_new_tokens=12)[0]
    gen = np.asarray(full[len(prompt):])
    eos = int(gen[3])
    first = int(np.argmax(gen == eos))

    srv = ServingEngine(engine, num_slots=2, max_queue_depth=4,
                        spec_decode=_spec(k=5))
    req = srv.submit(prompt, max_new_tokens=12, eos_token_id=eos)
    srv.run_until_drained(max_steps=50)
    assert req.finish_reason == "eos"
    np.testing.assert_array_equal(req.output_tokens, gen[:first + 1])


def test_do_sample_spec_smoke(stack):
    """Lossless rejection sampling path: runs, respects budgets, emits
    in-vocab tokens. (Distributional identity is the verify program's
    math; this guards the plumbing.)"""
    _, _, engine = stack
    rng = np.random.default_rng(29)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                        do_sample=True, temperature=1.0, seed=5,
                        spec_decode=_spec(k=3))
    reqs = [srv.submit(p, max_new_tokens=6)
            for p in _mixed_prompts(rng, 4)]
    srv.run_until_drained(max_steps=100)
    for r in reqs:
        assert r.state == RequestState.FINISHED
        assert len(r.output_tokens) == 6
        assert all(0 <= t < 64 for t in r.output_tokens)


# ------------------------------------------------------- shape discipline
def test_spec_churn_does_not_recompile(stack):
    """Slot retire/admit churn with speculation on keeps the verify jit
    (and decode/prefill jits) at a fixed program count — draft_len
    masking absorbs every live/dead/non-speculating combination."""
    _, _, engine = stack
    rng = np.random.default_rng(31)

    def wave(n):
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=16,
                            spec_decode=_spec(k=4))
        for p in _mixed_prompts(rng, n):
            srv.submit(p, max_new_tokens=5)
        srv.run_until_drained(max_steps=200)
        return srv

    wave(2)  # compile: prefill buckets, verify, decode
    n_verify = engine._jit_verify_k._cache_size()
    n_decode = engine._jit_decode._cache_size()
    n_prefill = engine._jit_prefill_at._cache_size()
    srv = wave(6)  # multi-wave churn through the same shapes
    assert engine._jit_verify_k._cache_size() == n_verify
    assert engine._jit_decode._cache_size() == n_decode
    assert engine._jit_prefill_at._cache_size() == n_prefill
    # the watchdog pins the same invariant at runtime: a warmed server
    # sees zero attributed compiles through another churn wave
    srv.end_warmup()
    for p in _mixed_prompts(rng, 4):
        srv.submit(p, max_new_tokens=5)
    srv.run_until_drained(max_steps=200)
    assert srv.watchdog.recompiles == 0


def test_capacity_margin_tightens_admission(stack):
    """With spec on, admission reserves k positions of verify headroom:
    a request that fits the raw capacity but not capacity - k is shed
    as prompt_too_long instead of corrupting a neighbour's live KV."""
    _, _, engine = stack
    prompt = np.zeros((40,), np.int32)  # 40 + 20 = 60 <= 64 but > 64 - 6
    off = ServingEngine(engine, num_slots=2, max_queue_depth=4)
    assert off.submit(prompt, max_new_tokens=20).state == RequestState.QUEUED
    on = ServingEngine(engine, num_slots=2, max_queue_depth=4,
                       spec_decode=_spec(k=6))
    r = on.submit(prompt, max_new_tokens=20)
    assert r.state == RequestState.REJECTED
    assert r.reject_reason == "prompt_too_long"
    assert on.submit(prompt, max_new_tokens=18).state == RequestState.QUEUED


# -------------------------------------------------------------- drafters
def test_ngram_drafter_unit():
    d = NGramDrafter(max_ngram=3, min_ngram=1)
    h = np.array([1, 2, 3, 1, 2, 3, 1, 2], np.int32)
    tokens, counts = d.propose([h, None, np.array([9], np.int32)], k=3)
    assert tokens.shape == (3, 3) and counts.shape == (3,)
    # suffix [3,1,2] recurs at position 2; continuation is h[5:8]
    np.testing.assert_array_equal(tokens[0], [3, 1, 2])
    assert counts[0] == 3
    assert counts[1] == 0 and counts[2] == 0  # dead slot, too-short history

    # continuation clipped by history end -> partial count
    tokens, counts = d.propose([np.array([5, 6, 5, 6, 5], np.int32)], k=4)
    assert 0 < counts[0] <= 4
    np.testing.assert_array_equal(
        tokens[0, :counts[0]],
        np.array([6, 5, 6, 5], np.int32)[:counts[0]])

    # no repeated suffix anywhere -> no proposal
    _, counts = d.propose([np.arange(10, dtype=np.int32)], k=3)
    assert counts[0] == 0


def test_small_model_drafter_self_speculation(stack):
    """Drafting with the TARGET model itself (the degenerate two-model
    setup) must keep exact parity — and accept nearly everything, since
    the draft IS the target's greedy continuation."""
    model, params, engine = stack
    draft_eng = ds.init_inference(model=model, model_parameters=params,
                                  config={"dtype": "float32"})
    rng = np.random.default_rng(37)
    prompts = _mixed_prompts(rng, 4)

    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                        spec_decode={"drafter": "model", "k": 4,
                                     "draft_engine": draft_eng})
    reqs = [srv.submit(p, max_new_tokens=10) for p in prompts]
    srv.run_until_drained(max_steps=100)
    for r, p in zip(reqs, prompts):
        expected = engine.generate(p[None], max_new_tokens=10)[0]
        np.testing.assert_array_equal(r.tokens(), expected)
    s = srv.stats()
    assert s["spec_acceptance_rate"] > 0.8
    assert s["tokens_per_decode_step"] > 2.0


# ------------------------------------------------------- config + rollback
def test_spec_config_validation():
    assert SpecDecodeConfig.from_value(None) is None
    assert SpecDecodeConfig.from_value(False) is None
    cfg = SpecDecodeConfig.from_value(True)
    assert cfg.enabled and cfg.drafter == "ngram" and cfg.k == 4
    assert SpecDecodeConfig.from_value({"k": 2}).k == 2
    sc = SpecDecodeConfig.from_value(cfg)
    assert sc is cfg
    with pytest.raises(TypeError, match="spec_decode"):
        SpecDecodeConfig.from_value(7)
    with pytest.raises(ValueError, match="k"):
        SpecDecodeConfig(k=0).validate(64)
    with pytest.raises(ValueError, match="capacity"):
        SpecDecodeConfig(k=63).validate(64)
    with pytest.raises(ValueError, match="min_ngram"):
        SpecDecodeConfig(min_ngram=0).validate(64)
    with pytest.raises(ValueError, match="draft_engine"):
        make_drafter(SpecDecodeConfig(drafter="model"))
    with pytest.raises(ValueError, match="unknown drafter"):
        make_drafter(SpecDecodeConfig(drafter="nope"))
    d = NGramDrafter()
    assert make_drafter(SpecDecodeConfig(drafter=d)) is d


def test_slot_pool_advance_per_slot(stack):
    """advance(array) is the rollback primitive: the host mirror AND the
    device index move per slot; advance(scalar) moves only the mirror
    (the in-jit uniform bump already moved the device side)."""
    _, _, engine = stack
    pool = SlotPool(engine.kv_cache_spec(), 3)
    pool.starts[:] = [5, 9, 2]
    pool.advance(np.array([3, 0, 1], np.int32))
    np.testing.assert_array_equal(pool.starts, [8, 9, 3])
    np.testing.assert_array_equal(
        np.asarray(pool.cache["cache_store"]["index"]), [8, 9, 3])
    pool.advance(1)  # scalar: mirror only
    np.testing.assert_array_equal(pool.starts, [9, 10, 4])
    np.testing.assert_array_equal(
        np.asarray(pool.cache["cache_store"]["index"]), [8, 9, 3])
    with pytest.raises(ValueError, match="shape"):
        pool.advance(np.zeros((2,), np.int32))
