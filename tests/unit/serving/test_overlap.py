"""Pipelined-step (``overlap=True``) tests (ISSUE 13): overlap reorders
WHEN host bookkeeping happens — decode dispatches before admission's host
work, token fetches collapse onto one end-of-step sync — but never WHAT
is computed. Every outcome (tokens, finish reasons, terminal timeline
events) must be bitwise what the serial step produces, across the plain,
paged-kernel and speculative configurations, including preempt/resume;
the deferred-fetch queue must always drain by the step boundary."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import RequestState, ServingEngine

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)
PS = 8


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


def make_srv(engine, overlap, num_slots=3, **kw):
    kw.setdefault("prefill_chunk", PS)
    return ServingEngine(engine, num_slots=num_slots, max_queue_depth=32,
                         overlap=overlap, **kw)


def _workload(seed=11, n=8):
    rng = np.random.default_rng(seed)
    lengths = rng.integers(3, 22, size=n)
    prompts = [rng.integers(0, 64, size=int(T)).astype(np.int32)
               for T in lengths]
    budgets = [int(b) for b in rng.integers(3, 10, size=n)]
    return prompts, budgets


def run_traffic(srv, prompts, budgets, max_steps=600):
    reqs = [srv.submit(p, max_new_tokens=b) for p, b in zip(prompts, budgets)]
    srv.run_until_drained(max_steps=max_steps)
    srv.check_invariants()
    assert not srv._deferred, "deferred fetches leaked past the drain"
    return reqs


@pytest.mark.parametrize("extra", [
    {},
    {"paged_kv": {"page_size": PS, "kernel": "on"}},
    {"spec_decode": {"k": 3, "drafter": "ngram"}},
], ids=["plain", "paged-kernel", "spec"])
def test_overlap_outcome_parity(stack, extra):
    """Same staggered workload through overlap and serial servers: every
    request must finish with identical tokens, identical finish reason,
    and identical first/terminal timeline events."""
    _, _, engine = stack
    prompts, budgets = _workload()
    srv_s = make_srv(engine, overlap=False, **extra)
    srv_o = make_srv(engine, overlap=True, **extra)
    assert not srv_s._overlap and srv_o._overlap
    serial = run_traffic(srv_s, prompts, budgets)
    over = run_traffic(srv_o, prompts, budgets)
    for a, b in zip(serial, over):
        assert a.state == RequestState.FINISHED, a.finish_reason
        assert a.finish_reason == b.finish_reason
        np.testing.assert_array_equal(a.tokens(), b.tokens())
        ev_a = srv_s.timelines.events_of(a.request_id)
        ev_b = srv_o.timelines.events_of(b.request_id)
        assert ev_a[0] == ev_b[0] and ev_a[-1] == ev_b[-1]


def test_overlap_matches_generate(stack):
    """The pipelined path against the whole-batch oracle directly."""
    _, _, engine = stack
    prompts, budgets = _workload(seed=17, n=5)
    reqs = run_traffic(make_srv(engine, overlap=True), prompts, budgets)
    for req, p, b in zip(reqs, prompts, budgets):
        expected = engine.generate(np.asarray(p)[None],
                                   max_new_tokens=b)[0]
        np.testing.assert_array_equal(req.tokens(), expected)


def test_overlap_preempt_resume_parity(stack):
    """Preempting mid-decode while fetches are deferred: the rollback
    must observe fully-drained host state (no token applied twice, none
    lost) — the resumed request's output equals the serial arm's."""
    _, _, engine = stack
    rng = np.random.default_rng(23)
    prompt = rng.integers(0, 64, size=14).astype(np.int32)

    def run(overlap):
        srv = make_srv(engine, overlap=overlap, num_slots=2)
        req = srv.submit(prompt, max_new_tokens=10)
        for _ in range(4):
            srv.step()
        assert not srv._deferred          # step boundaries stay clean
        srv.preempt(req.request_id)
        assert req.preemptions == 1
        srv.run_until_drained(max_steps=200)
        srv.check_invariants()
        return req

    a, b = run(True), run(False)
    assert a.state == RequestState.FINISHED
    assert a.finish_reason == b.finish_reason
    np.testing.assert_array_equal(a.tokens(), b.tokens())


def test_overlap_defers_decode_fetches(stack):
    """The pipeline is real, not vacuous: with live decode slots, an
    overlap step queues its token fetches through _defer and drains them
    exactly once at the step boundary (the ONE deliberate sync)."""
    _, _, engine = stack
    srv = make_srv(engine, overlap=True, num_slots=2)
    drains, queued = [], []
    orig = srv._drain_deferred

    def spy(**kw):
        queued.append(len(srv._deferred))
        drains.append(kw)
        return orig(**kw)

    srv._drain_deferred = spy
    srv.submit(np.arange(6, dtype=np.int32), max_new_tokens=4)
    srv.run_until_drained(max_steps=100)
    srv._drain_deferred = orig
    srv.check_invariants()
    # at least one decode step queued a deferred fetch before draining
    assert any(n > 0 for n in queued)


def test_init_serving_forwards_overlap_and_kernel(stack):
    """`ds.init_serving(overlap=..., paged_kv={"kernel": ...})` must reach
    the ServingEngine, not leak into the inference-engine kwargs."""
    model, params, _ = stack
    srv = ds.init_serving(model=model, model_parameters=params,
                          config={"dtype": "float32"}, num_slots=2,
                          prefill_chunk=PS, overlap=True,
                          paged_kv={"page_size": PS, "kernel": "on"})
    assert srv._overlap
    assert srv.pool.kernel_active
    req = srv.submit(np.arange(5, dtype=np.int32), max_new_tokens=3)
    srv.run_until_drained(max_steps=100)
    srv.check_invariants()
    assert req.state == RequestState.FINISHED


def test_overlap_cancel_midflight(stack):
    """Cancel while a fetch may be in flight: the slot frees, invariants
    hold, and the other request's tokens are untouched."""
    _, _, engine = stack
    rng = np.random.default_rng(29)
    keep_p = rng.integers(0, 64, size=9).astype(np.int32)
    srv = make_srv(engine, overlap=True, num_slots=2)
    keep = srv.submit(keep_p, max_new_tokens=6)
    kill = srv.submit(rng.integers(0, 64, size=12).astype(np.int32),
                      max_new_tokens=20)
    for _ in range(3):
        srv.step()
    srv.cancel(kill.request_id)
    srv.run_until_drained(max_steps=100)
    srv.check_invariants()
    assert not srv._deferred
    assert keep.state == RequestState.FINISHED
    expected = engine.generate(np.asarray(keep_p)[None],
                               max_new_tokens=6)[0]
    np.testing.assert_array_equal(keep.tokens(), expected)
    assert kill.state != RequestState.RUNNING
