"""Paged KV pool tests: paging must be a pure MEMORY-LAYOUT change — greedy
tokens bitwise-match both whole-batch ``generate()`` and the contiguous
SlotPool under slot churn, prefix hits, copy-on-write forks, speculative
rollback across page boundaries, and preempt/resume; page churn never
recompiles; refcount bookkeeping survives the invariant audit; admission is
page-denominated."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import (PagedKVPool, PagePoolExhausted, PrefixCache,
                                   RejectReason, RequestState, ServingEngine)
from deepspeed_tpu.serving.resilience import InvariantViolation

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)
PS = 8  # page size == prefill chunk for every server in this file


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


def paged_server(engine, num_slots=2, num_pages=None, **kw):
    kw.setdefault("prefill_chunk", PS)
    return ServingEngine(engine, num_slots=num_slots, max_queue_depth=32,
                         paged_kv={"page_size": PS, "num_pages": num_pages},
                         **kw)


def run_traffic(srv, prompts, budgets):
    reqs = [srv.submit(p, max_new_tokens=b) for p, b in zip(prompts, budgets)]
    srv.run_until_drained(max_steps=400)
    return reqs


def assert_matches_generate(engine, reqs, prompts, budgets):
    for req, prompt, budget in zip(reqs, prompts, budgets):
        assert req.state == RequestState.FINISHED, req.finish_reason
        expected = engine.generate(np.asarray(prompt)[None],
                                   max_new_tokens=budget)[0]
        np.testing.assert_array_equal(req.tokens(), expected,
                                      err_msg=f"req {req.request_id}")


# ---------------------------------------------------------------------------
# bitwise parity


def test_paged_tokens_bitwise_match_generate(stack):
    """Multi-wave slot reuse through the paged pool must produce EXACTLY
    the tokens static-batch generate() produces — page tables are an
    addressing change, never a numerics change (greedy)."""
    _, _, engine = stack
    rng = np.random.default_rng(7)
    lengths = [5, 9, 12, 5, 17, 12]
    budgets = [6, 4, 8, 3, 7, 5]
    prompts = [rng.integers(0, 64, size=n).astype(np.int32) for n in lengths]
    srv = paged_server(engine)
    assert isinstance(srv.pool, PagedKVPool)
    reqs = run_traffic(srv, prompts, budgets)
    assert_matches_generate(engine, reqs, prompts, budgets)
    srv.check_invariants()


def test_paged_matches_contiguous_pool(stack):
    """The same staggered traffic through a paged and a contiguous server
    yields identical per-request tokens — pinning paged-vs-SlotPool parity
    directly, not just both-against-generate."""
    _, _, engine = stack
    rng = np.random.default_rng(13)
    prompts = [rng.integers(0, 64, size=n).astype(np.int32)
               for n in (6, 11, 24, 9, 6)]
    budgets = [5, 7, 4, 6, 8]
    paged = run_traffic(paged_server(engine), prompts, budgets)
    dense = run_traffic(
        ServingEngine(engine, num_slots=2, max_queue_depth=32,
                      prefill_chunk=PS), prompts, budgets)
    for p, d in zip(paged, dense):
        np.testing.assert_array_equal(p.tokens(), d.tokens())


# ---------------------------------------------------------------------------
# prefix cache


def test_prefix_hit_skips_prefill_and_keeps_parity(stack):
    """Requests sharing a 3-page prefix: followers must hit the trie (pay
    only the uncached suffix) AND still emit bitwise-identical tokens."""
    _, _, engine = stack
    base = list(range(1, 25))                    # 24 tokens = 3 full pages
    prompts = [np.asarray(base + [30 + i], np.int32) for i in range(4)]
    budgets = [5, 5, 5, 5]
    srv = paged_server(engine, num_slots=2)
    reqs = []
    for p, b in zip(prompts, budgets):           # drain between arrivals so
        reqs.append(srv.submit(p, max_new_tokens=b))   # the trie is warm
        srv.run_until_drained(max_steps=100)
    assert_matches_generate(engine, reqs, prompts, budgets)

    stats = srv.pool.page_stats()
    assert stats["prefix_hits"] >= 3             # every follower hit
    assert stats["prefix_hit_tokens"] >= 3 * 24
    assert reqs[0].prefix_hit_tokens == 0
    # pos0 is aligned DOWN to a chunk boundary; a 24-token hit on a
    # 25-token seed re-enters prefill at 24
    assert all(r.prefix_hit_tokens == 24 for r in reqs[1:])
    snap = srv.stats()
    assert snap["prefix_hits"] >= 3
    assert snap["prefix_hit_rate"] > 0
    assert snap["paging"]["pages_total"] == srv.pool.num_pages
    srv.check_invariants()


def test_cow_fork_on_page_aligned_duplicate(stack):
    """A page-aligned duplicate prompt full-hits the trie; re-prefilling
    the final chunk (to recover the next-token logits) lands inside a
    SHARED page and must fork it copy-on-write — with bitwise parity."""
    _, _, engine = stack
    dup = np.asarray([40] * 32, np.int32)        # 4 full pages exactly
    srv = paged_server(engine, num_slots=2)
    r1 = srv.submit(dup, max_new_tokens=4)
    srv.run_until_drained(max_steps=100)
    r2 = srv.submit(dup, max_new_tokens=4)
    srv.run_until_drained(max_steps=100)
    assert srv.pool.cow_copies >= 1
    assert r2.prefix_hit_tokens == 24            # full hit, last chunk redone
    expected = engine.generate(dup[None], max_new_tokens=4)[0]
    np.testing.assert_array_equal(r1.tokens(), expected)
    np.testing.assert_array_equal(r2.tokens(), expected)
    srv.check_invariants()


def test_prefix_cache_unit():
    """Trie semantics in isolation: full-page matching, peek neutrality,
    insert dedup, and leaf-LRU eviction order."""

    class FakePool:
        def __init__(self):
            self.refs = {}

        def ref_page(self, pid):
            self.refs[pid] = self.refs.get(pid, 0) + 1

        def unref_page(self, pid):
            self.refs[pid] -= 1
            return self.refs[pid] == 0

    pool, trie = FakePool(), PrefixCache(4)
    a = list(range(12))                          # 3 full pages
    assert trie.match(a) == [] and trie.misses == 1
    trie.insert(a, [10, 11, 12], pool)
    assert pool.refs == {10: 1, 11: 1, 12: 1}
    assert trie.peek(a) == 3 and trie.hits == 0  # peek leaves counters alone
    assert trie.match(a) == [10, 11, 12] and trie.hits == 1
    assert trie.match(a[:10]) == [10, 11]        # partial page dropped
    assert trie.match([9] * 8) == []             # divergent first page
    trie.insert(a, [20, 21, 22], pool)           # dedup: keeps older pages
    assert trie.num_nodes == 3 and 20 not in pool.refs

    b = a[:8] + [50, 51, 52, 53]                 # shares 2 pages, forks 3rd
    trie.insert(b, [10, 11, 30], pool)
    assert trie.num_nodes == 4
    trie.match(b)                                # stamp b's branch young
    assert trie.evict(pool, need=1) == 1         # LRU leaf = a's page 12
    assert 12 not in [n for n in pool.refs if pool.refs[n] > 0]
    assert trie.match(a) == [10, 11]
    trie.clear(pool)
    assert trie.num_nodes == 0
    assert all(v == 0 for v in pool.refs.values())


# ---------------------------------------------------------------------------
# speculative decoding / preemption composition


def test_spec_decode_paged_parity_across_page_boundary(stack):
    """Draft-verify over the paged pool: the K+1-wide verify window and
    its rollback regularly straddle page boundaries (budget spans several
    pages); greedy output must stay bitwise-identical to generate()."""
    _, _, engine = stack
    rng = np.random.default_rng(23)
    prompts = [rng.integers(0, 8, size=n).astype(np.int32)
               for n in (6, 14, 10)]             # small vocab => ngram hits
    budgets = [20, 18, 16]                       # crosses 2-3 page boundaries
    srv = paged_server(engine, num_slots=2,
                       spec_decode={"drafter": "ngram", "k": 3})
    reqs = run_traffic(srv, prompts, budgets)
    assert_matches_generate(engine, reqs, prompts, budgets)
    srv.check_invariants()


def test_preempt_resume_with_cached_prefix(stack):
    """Preempt mid-decode, resume through the paged pool: the re-prefill
    walks the prefix cache (the preempted prompt's own full pages are
    trie-cached) and the final tokens are bitwise what an unpreempted run
    produces."""
    _, _, engine = stack
    rng = np.random.default_rng(31)
    prompt = rng.integers(0, 64, size=18).astype(np.int32)
    srv = paged_server(engine, num_slots=2)
    req = srv.submit(prompt, max_new_tokens=12)
    for _ in range(4):                           # partway through decode
        srv.step()
    srv.preempt(req.request_id)
    assert req.preemptions == 1
    srv.run_until_drained(max_steps=200)
    assert_matches_generate(engine, [req], [prompt], [12])
    assert req.prefix_hit_tokens > 0             # resume hit its own pages
    srv.check_invariants()


# ---------------------------------------------------------------------------
# zero-recompile + pressure


def test_no_recompile_after_warmup_page_churn(stack):
    """Strict watchdog: once warm traffic has covered prefill, decode,
    prefix hits, and a CoW fork, page churn (new tables, eviction,
    oversubscription pressure) must never recompile a paged program."""
    _, _, engine = stack
    srv = paged_server(engine, num_slots=4, num_pages=12,
                       preempt_queue_threshold=2, strict_recompile=True)
    base = list(range(1, 25))
    for i in range(3):
        srv.submit(np.asarray(base + [30 + i], np.int32), max_new_tokens=6)
    srv.run_until_drained(max_steps=200)
    dup = np.asarray([40] * 32, np.int32)
    for _ in range(2):                           # 2nd dup full-hits -> CoW
        srv.submit(dup, max_new_tokens=4)
        srv.run_until_drained(max_steps=100)
    assert srv.pool.cow_copies >= 1
    srv.end_warmup()

    srv.submit(dup, max_new_tokens=4)            # post-warmup CoW fork
    for i in range(8):                           # oversubscription churn
        srv.submit(np.asarray(base + [50 + i], np.int32), max_new_tokens=8)
    srv.run_until_drained(max_steps=400)
    assert srv.watchdog.recompiles == 0
    srv.check_invariants()


def test_oversubscribed_pool_drains_under_pressure(stack):
    """num_pages far below worst-case: admission throttles on the page
    budget, trie eviction and pressure preemption reclaim pages, and every
    request still finishes with exact tokens."""
    _, _, engine = stack
    rng = np.random.default_rng(41)
    prompts = [rng.integers(0, 64, size=n).astype(np.int32)
               for n in (20, 24, 20, 24, 20, 24)]
    budgets = [10, 8, 10, 8, 10, 8]
    # worst case is 4 slots * 64 tokens = 32 pages; give it 12
    srv = paged_server(engine, num_slots=4, num_pages=12,
                       preempt_queue_threshold=2,
                       degradation={"queue_pressured": 4,
                                    "queue_overloaded": 12,
                                    "cooldown_steps": 2})
    reqs = run_traffic(srv, prompts, budgets)
    assert_matches_generate(engine, reqs, prompts, budgets)
    assert srv.pool.free_page_count + srv.pool.prefix.num_nodes \
        <= srv.pool.num_pages
    # page starvation must register as load even with a short queue —
    # the degradation ladder is page-denominated under oversubscription
    assert srv.stats()["load_transitions"] >= 1
    srv.check_invariants()


def test_page_denominated_admission_rejects(stack):
    """A prompt whose page footprint exceeds the whole pool is rejected at
    submit with PROMPT_TOO_LONG — page-denominated admission control."""
    _, _, engine = stack
    srv = paged_server(engine, num_slots=2, num_pages=4)   # 32 tokens total
    rng = np.random.default_rng(43)
    req = srv.submit(rng.integers(0, 64, size=40).astype(np.int32),
                     max_new_tokens=8)
    assert req.state == RequestState.REJECTED
    assert req.reject_reason == RejectReason.PROMPT_TOO_LONG
    ok = srv.submit(rng.integers(0, 64, size=10).astype(np.int32),
                    max_new_tokens=4)
    srv.run_until_drained(max_steps=100)
    assert ok.state == RequestState.FINISHED
    srv.check_invariants()


# ---------------------------------------------------------------------------
# bookkeeping integrity


def test_invariant_audit_catches_refcount_corruption(stack):
    """The page audit must detect a refcount that no held reference
    explains — the chaos-suite contract extended to page bookkeeping."""
    _, _, engine = stack
    srv = paged_server(engine, num_slots=2)
    srv.submit(np.arange(1, 20, dtype=np.int32), max_new_tokens=4)
    srv.run_until_drained(max_steps=100)
    srv.check_invariants()                       # clean before corruption
    pool = srv.pool
    victim = int(pool.table[0, 0]) if int(pool.table[0, 0]) != pool.num_pages \
        else next(iter(pool.prefix.page_counts()))
    pool.page_refs[victim] += 1                  # phantom reference
    with pytest.raises(InvariantViolation, match="page"):
        srv.check_invariants()
    pool.page_refs[victim] -= 1
    srv.check_invariants()


def test_paging_telemetry_gauges_and_stats(stack):
    """stats() carries the paging panel and the registry exports the
    paging/* gauges every step."""
    _, _, engine = stack
    srv = paged_server(engine, num_slots=2)
    srv.submit(np.arange(1, 15, dtype=np.int32), max_new_tokens=3)
    srv.run_until_drained(max_steps=100)
    snap = srv.stats()
    paging = snap["paging"]
    for key in ("pages_total", "pages_free", "pages_in_use",
                "refcounted_pages", "cow_copies", "page_evictions",
                "page_size", "prefix_hits", "prefix_misses"):
        assert key in paging
    assert paging["pages_total"] == paging["pages_free"] \
        + paging["pages_in_use"]
    sample = srv.registry.snapshot()
    assert "paging/free_pages" in sample
    assert "paging/pages_in_use" in sample
    text = srv.registry.to_prometheus()
    assert "paging" in text
