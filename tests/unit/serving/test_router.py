"""Data-parallel replica router tests: the router must be a pure
DISPATCH layer — routing, spill, and failover can never change model
output (greedy tokens bitwise-match a single engine), ids stay globally
unique, session/prefix affinity beats least-loaded deterministically,
and a replica lost mid-request re-homes its work to a sibling with zero
slot or page leaks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import (ID_STRIDE, FinishReason,
                                   NoLiveReplicaError, ReplicaRouter,
                                   RequestState, ServingEngine)

TINY = dict(vocab_size=64, max_seq_len=128, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


def _mk(engine, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_queue_depth", 16)
    return ServingEngine(engine, **kw)


def _prompts(n, rng, lo=5, hi=12):
    return [rng.integers(0, 64, size=int(rng.integers(lo, hi + 1)))
            .astype(np.int32) for _ in range(n)]


def test_router_matches_single_engine_bitwise(stack):
    """Routing over two replicas is invisible to the client: greedy
    outputs bitwise-match the same workload through one engine."""
    _, _, engine = stack
    rng = np.random.default_rng(11)
    prompts = _prompts(8, rng)
    budgets = [int(rng.integers(3, 8)) for _ in prompts]

    solo = _mk(engine)
    ref = [solo.submit(p, max_new_tokens=b)
           for p, b in zip(prompts, budgets)]
    solo.run_until_drained(max_steps=400)

    router = ReplicaRouter([_mk(engine), _mk(engine)])
    got = [router.submit(p, max_new_tokens=b)
           for p, b in zip(prompts, budgets)]
    router.run_until_drained(max_steps=400)

    for r, g in zip(ref, got):
        assert g.state == RequestState.FINISHED
        np.testing.assert_array_equal(g.output_tokens, r.output_tokens)


def test_router_ids_globally_unique(stack):
    """Replica i issues ids in [i*ID_STRIDE, (i+1)*ID_STRIDE): a
    router-issued id names one request regardless of seat."""
    _, _, engine = stack
    rng = np.random.default_rng(5)
    router = ReplicaRouter([_mk(engine), _mk(engine), _mk(engine)])
    reqs = [router.submit(p, max_new_tokens=2) for p in _prompts(9, rng)]
    ids = [r.request_id for r in reqs]
    assert len(set(ids)) == len(ids)
    for r in reqs:
        owner = router._owner[r.request_id]
        assert r.request_id // ID_STRIDE == owner
    router.run_until_drained(max_steps=400)


def test_owner_map_retired_with_tracking(stack):
    """Router bookkeeping may not outlive a request: finishing,
    cancelling and unplaceable-failover all retire the ``_owner`` entry
    alongside ``_tracked`` (regression: ``_owner`` kept every id ever
    routed, an unbounded host-side leak graftown's
    leak-on-exception-path family is built to catch)."""
    _, _, engine = stack
    rng = np.random.default_rng(7)
    router = ReplicaRouter([_mk(engine), _mk(engine)])
    reqs = [router.submit(p, max_new_tokens=3) for p in _prompts(6, rng)]
    assert len(router._owner) == len(reqs)

    victim = reqs[-1]
    assert router.cancel(victim.request_id) is not None
    assert victim.request_id not in router._owner
    assert victim.request_id not in router._tracked

    router.run_until_drained(max_steps=400)
    assert router._tracked == {}
    assert router._owner == {}
    router.check_invariants()


def test_failover_requeues_to_sibling_bitwise(stack):
    """A replica that dies MID-REQUEST (some tokens already generated)
    re-homes every owed request to the sibling; greedy resume via
    ``seed_tokens`` is bitwise identical to never having failed."""
    _, _, engine = stack
    rng = np.random.default_rng(23)
    prompts = _prompts(6, rng)
    budgets = [6] * len(prompts)

    solo = _mk(engine, num_slots=2, max_queue_depth=16)
    ref = [solo.submit(p, max_new_tokens=b)
           for p, b in zip(prompts, budgets)]
    solo.run_until_drained(max_steps=400)

    rep_a, rep_b = _mk(engine), _mk(engine)
    router = ReplicaRouter([rep_a, rep_b])
    got = [router.submit(p, max_new_tokens=b)
           for p, b in zip(prompts, budgets)]
    # let both replicas make partial progress, then kill replica 0
    # mid-decode: its seated requests have output_tokens already
    router.step()
    router.step()
    assert any(r.output_tokens for r in got)
    boom = RuntimeError("injected replica loss")
    original_step = rep_a.step

    def dying_step():
        raise boom

    rep_a.step = dying_step
    fins = router.run_until_drained(max_steps=800)
    rep_a.step = original_step

    assert router.alive_replicas == [1]
    assert router.failovers > 0
    assert len(fins) >= 1
    for r, g in zip(ref, got):
        assert g.state == RequestState.FINISHED
        assert g.finish_reason == FinishReason.LENGTH
        np.testing.assert_array_equal(g.output_tokens, r.output_tokens)
    # the survivor's books must balance; the corpse is a tombstone
    router.check_invariants()
    assert rep_b.pool.free_count == rep_b.pool.num_slots
    assert rep_b.live_count == 0 and rep_b.scheduler.pending == 0


def test_all_replicas_dead_raises(stack):
    _, _, engine = stack
    rng = np.random.default_rng(2)
    rep = _mk(engine)
    router = ReplicaRouter([rep])
    router.submit(_prompts(1, rng)[0], max_new_tokens=4)
    rep.step = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    with pytest.raises(NoLiveReplicaError):
        router.run_until_drained(max_steps=10)


def test_affinity_vs_least_loaded_tiebreak_deterministic(stack):
    """Dispatch priority is sticky-session -> prefix-peek -> least
    loaded -> lowest index, and two routers fed the same sequence
    dispatch identically (the determinism pin)."""
    _, _, engine = stack
    rng = np.random.default_rng(9)
    page = 8
    shared = rng.integers(0, 64, size=3 * page).astype(np.int32)

    def build():
        reps = [
            _mk(engine, prefill_chunk=page,
                paged_kv={"page_size": page, "num_pages": 16}),
            _mk(engine, prefill_chunk=page,
                paged_kv={"page_size": page, "num_pages": 16}),
        ]
        return ReplicaRouter(reps), reps

    def drive(router):
        trace = []
        # 1) empty tries, equal load: lowest index wins
        r = router.submit(shared, max_new_tokens=2)
        trace.append(router._owner[r.request_id])
        router.run_until_drained(max_steps=200)
        # 2) replica 0 now caches the shared prefix; load replica 1
        #    being idle must NOT steal a prefix-affine prompt
        busy = router.replicas[0].submit(
            rng.integers(0, 64, size=5).astype(np.int32), max_new_tokens=6)
        r = router.submit(
            np.concatenate([shared,
                            rng.integers(0, 64, size=3).astype(np.int32)]),
            max_new_tokens=2)
        trace.append(router._owner[r.request_id])
        # 3) a cold prompt goes least-loaded (replica 1), not index 0
        r = router.submit(rng.integers(0, 64, size=2 * page)
                          .astype(np.int32), max_new_tokens=2)
        trace.append(router._owner[r.request_id])
        # 4) session pin beats both: with replica 0 strictly busier, a
        #    cold session request homes on 1; the follow-up turn carries
        #    a prompt whose prefix lives on 0 — stickiness wins anyway
        busy2 = router.replicas[0].submit(
            rng.integers(0, 64, size=5).astype(np.int32), max_new_tokens=6)
        r = router.submit(rng.integers(0, 64, size=6).astype(np.int32),
                          session="s1", max_new_tokens=2)
        home = router._owner[r.request_id]
        trace.append(home)
        del busy2
        r = router.submit(
            np.concatenate([shared,
                            rng.integers(0, 64, size=2).astype(np.int32)]),
            session="s1", max_new_tokens=2)
        trace.append(router._owner[r.request_id])
        router.run_until_drained(max_steps=400)
        del busy
        return trace

    router1, _ = build()
    t1 = drive(router1)
    assert t1[0] == 0          # lowest-index tie-break
    assert t1[1] == 0          # prefix affinity beats idle sibling
    assert t1[2] == 1          # least-loaded for cold prompts
    assert t1[3] == 1          # cold session homes least-loaded
    assert t1[4] == 1          # session stickiness beats prefix score
    assert router1.affinity_hits > 0

    router2, _ = build()
    t2 = drive(router2)
    assert t1 == t2            # identical sequence -> identical dispatch


def test_router_zero_leaks_after_failover_and_drain(stack):
    """After spills, failover and a full drain, no replica leaks a slot
    or a page: free counts match pool sizes and check_invariants holds
    on every ALIVE replica (paged pools included)."""
    _, _, engine = stack
    rng = np.random.default_rng(31)
    page = 8

    def mk_paged():
        return _mk(engine, prefill_chunk=page, max_queue_depth=8,
                   paged_kv={"page_size": page, "num_pages": 12})

    rep_a, rep_b, rep_c = mk_paged(), mk_paged(), mk_paged()
    router = ReplicaRouter([rep_a, rep_b, rep_c])
    reqs = [router.submit(p, max_new_tokens=4)
            for p in _prompts(10, rng, lo=6, hi=20)]
    router.step()
    rep_b.step = lambda: (_ for _ in ()).throw(RuntimeError("gone"))
    router.run_until_drained(max_steps=800)

    assert router.alive_replicas == [0, 2]
    router.check_invariants()
    for rep in (rep_a, rep_c):
        assert rep.live_count == 0
        assert rep.scheduler.pending == 0
        assert rep.pool.free_count == rep.pool.num_slots
        # every page is either free or held only by the prefix cache
        stats = rep.pool.page_stats()
        assert stats["pages_in_use"] == stats["prefix_evictable_pages"]
    placed = [r for r in reqs if r.state == RequestState.FINISHED]
    assert len(placed) == len(reqs)  # nobody stranded by the failover
