"""Efficiency/goodput observability through the serving engine: the
flight recorder must produce exactly one schema-pinned post-mortem per
planted invariant violation, ``debug_dump`` must serve the same payload
live, the cost model must never perturb serving outputs, the SLO
tracker must count failures against goodput, and the telemetry-health
collector must surface tracer/sink/recorder counters in Prometheus."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import InvariantViolation, ServingEngine
from deepspeed_tpu.serving.resilience import FaultInjector
from deepspeed_tpu.telemetry.flight_recorder import (POST_MORTEM_KEYS,
                                                     SCHEMA_VERSION)

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


def _prompts(rng, n, lo=5, hi=12):
    return [rng.integers(0, 64, size=int(rng.integers(lo, hi)))
            .astype(np.int32) for _ in range(n)]


def test_postmortem_on_planted_invariant_violation(stack, tmp_path):
    _, _, engine = stack
    rng = np.random.default_rng(71)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                        fault_injector=FaultInjector(seed=0),
                        dump_dir=str(tmp_path))
    srv.faults.load_schedule({"state_corruption": [1]})
    for p in _prompts(rng, 2):
        srv.submit(p, max_new_tokens=4)
    srv.step()              # corruption fires at this step's tail
    with pytest.raises(InvariantViolation):
        srv.check_invariants()

    files = sorted(tmp_path.glob("postmortem-*.json"))
    assert len(files) == 1          # exactly one per planted violation
    with open(files[0]) as f:
        pm = json.load(f)
    # the file shape external tooling relies on, pinned
    assert sorted(pm) == sorted(POST_MORTEM_KEYS)
    assert pm["schema_version"] == SCHEMA_VERSION
    assert pm["reason"] == "invariant_violation"
    assert "free" in pm["error"]            # the corrupted free set
    assert pm["extra"]["violations"]
    # the last ring record is the step the corruption landed in
    last = pm["steps"][-1]
    assert last["step_id"] == srv.step_id
    assert last["live"] == 2
    for key in ("t_unix", "wall_ms", "pending", "prefilling", "free_slots",
                "granted", "finished", "tokens_total", "load_state",
                "alert_state"):
        assert key in last
    assert srv.recorder.dump_count == 1


def test_debug_dump_serves_postmortem_payload_live(stack):
    _, _, engine = stack
    rng = np.random.default_rng(73)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8, slo=True)
    for p in _prompts(rng, 3):
        srv.submit(p, max_new_tokens=8)
    for _ in range(2):
        srv.step()
    d = srv.debug_dump()            # healthy process, no files written
    assert d["schema_version"] == SCHEMA_VERSION
    assert d["step_id"] == 2 and d["live"] >= 1
    assert len(d["steps"]) == 2
    assert d["watchdog"]["recompiles"] == 0
    assert d["telemetry_overhead_s"] >= 0.0
    # 3 admitted, none finished yet: goodput is legitimately burning
    assert d["slo"]["alert_state"] in ("ok", "warn", "page")
    assert d["slo"]["admitted"] == 3
    assert isinstance(d["requests"], (list, dict))
    srv.run_until_drained(max_steps=100)
    assert srv.recorder.dump_count == 0


def test_cost_model_never_perturbs_outputs(stack):
    _, _, engine = stack
    rng = np.random.default_rng(79)
    prompts = _prompts(rng, 6)

    def run(cost_model):
        srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                            cost_model=cost_model)
        reqs = [srv.submit(p, max_new_tokens=5) for p in prompts]
        srv.run_until_drained(max_steps=200)
        return [list(r.output_tokens) for r in reqs]

    assert run(False) == run(True)  # greedy serving is bit-identical


def test_cost_model_harvests_and_reconciles(stack):
    _, _, engine = stack
    rng = np.random.default_rng(83)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                        cost_model=True)
    for p in _prompts(rng, 4):
        srv.submit(p, max_new_tokens=4)
    srv.run_until_drained(max_steps=200)
    cs = srv.costs.summary()
    assert cs["programs"] >= 1 and cs["flops_total"] > 0
    assert cs["unavailable"] == 0           # XLA:CPU serves cost_analysis
    eff = srv.efficiency_snapshot()
    assert eff["mfu"] > 0.0
    assert eff["hbm_drift"] == 0.0          # page math == device bytes
    assert eff["hbm_peak_bytes"] > 0
    assert eff["telemetry_overhead_s"] > 0.0
    assert 0.0 <= eff["overhead_pct"]


def test_slo_counts_deadline_expiry_against_goodput(stack):
    _, _, engine = stack
    rng = np.random.default_rng(89)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                        slo={"ttft_ms": 60_000.0, "gap_ms": 60_000.0,
                             "window_steps": 8})
    good = [srv.submit(p, max_new_tokens=3) for p in _prompts(rng, 3)]
    srv.run_until_drained(max_steps=100)
    assert srv.slo.goodput() == 1.0
    # an expired deadline finishes with reason=deadline -> not good
    # service no matter how fast it failed
    srv.submit(_prompts(rng, 1)[0], max_new_tokens=3, deadline_ms=1e-3)
    srv.step()
    snap = srv.slo.snapshot()
    assert snap["admitted"] == len(good) + 1
    assert snap["good"] == len(good)
    assert srv.slo.goodput() == pytest.approx(len(good) / (len(good) + 1))
    eff = srv.efficiency_snapshot()
    assert eff["goodput_slo"] == pytest.approx(snap["good"]
                                               / snap["admitted"])
    assert eff["alert_state"] in ("ok", "warn", "page")


def test_prometheus_exposes_telemetry_health(stack):
    _, _, engine = stack

    class _Sink:
        enabled = True
        write_errors = 3                    # a bare JSONL-style sink

        def write_events(self, events):
            pass

    rng = np.random.default_rng(97)
    srv = ServingEngine(engine, num_slots=2, max_queue_depth=8,
                        tracer=True, monitor=_Sink())
    for p in _prompts(rng, 2):
        srv.submit(p, max_new_tokens=3)
    srv.run_until_drained(max_steps=100)
    text = srv.registry.to_prometheus()
    assert "telemetry_tracer_events_total" in text
    assert "telemetry_tracer_dropped" in text
    assert "telemetry_flight_recorder_records" in text
    assert "telemetry_postmortem_dumps" in text
    assert "monitor_jsonl_write_errors 3" in text
    snap = srv.registry.snapshot()
    assert snap["telemetry/tracer_events_total"] > 0
    assert snap["telemetry/flight_recorder_records"] == srv.step_id
