"""Disaggregated prefill/decode serving: cross-pool page transfer,
role-aware routing, elastic scale events.

The oracle is the same one every paging test leans on: static-batch
``generate()`` greedy tokens. A transferred page is EXACTLY the bits
the prefill replica wrote, so a disaggregated fleet must be bitwise
identical to a single colocated engine — any drift means the transfer
primitive corrupted a page or seated it at the wrong table entry.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import RequestState, ServingEngine
from deepspeed_tpu.serving.router import ReplicaRouter

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)
PS = 8  # page size == prefill chunk for every server in this file

LENGTHS = [5, 9, 12, 5, 17, 12]
BUDGETS = [6, 4, 8, 3, 7, 5]


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


def paged_server(engine, role="both", **kw):
    kw.setdefault("prefill_chunk", PS)
    return ServingEngine(engine, num_slots=2, max_queue_depth=32,
                         paged_kv={"page_size": PS, "num_pages": None},
                         role=role, **kw)


def _prompts(seed):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 64, size=n).astype(np.int32) for n in LENGTHS]


def _warm(router, *, max_steps=600):
    """Drive one full shape population through the fleet, then arm the
    watchdogs: admit widths, decode, sampling AND the transfer program
    all record their signatures before end_warmup."""
    reqs = [router.submit(p, max_new_tokens=b)
            for p, b in zip(_prompts(3), BUDGETS)]
    router.run_until_drained(max_steps=max_steps)
    assert all(r.state is RequestState.FINISHED for r in reqs)
    router.end_warmup()


def _spawn_factory(engine, **kw):
    """Elastic provisioner: a newcomer must arrive TRAFFIC-WARMED (the
    constructor pre-warm does not cover admit/decode/sample widths), so
    the factory drives the warm population standalone before handing
    the replica to ``add_replica``."""
    wprompts = _prompts(3)

    def spawn(role):
        rep = paged_server(engine, role=role, **kw)
        if role != "prefill":
            w = [rep.submit(p, max_new_tokens=b)
                 for p, b in zip(wprompts, BUDGETS)]
            rep.run_until_drained(max_steps=600)
            assert all(r.state is RequestState.FINISHED for r in w)
        else:
            # prefill-role replicas never decode: warm by prefilling to
            # the parked-handoff state, then cancel
            for p, b in zip(wprompts, BUDGETS):
                r = rep.submit(p, max_new_tokens=b)
                for _ in range(40):
                    rep.step()
                    if r in rep.pending_handoffs():
                        break
                rep.cancel(r.request_id)
        return rep

    return spawn


def _assert_bitwise(engine, reqs, prompts, budgets):
    for req, prompt, budget in zip(reqs, prompts, budgets):
        assert req.state is RequestState.FINISHED, (
            req.request_id, req.state, req.finish_reason)
        expected = engine.generate(np.asarray(prompt)[None],
                                   max_new_tokens=budget)[0]
        np.testing.assert_array_equal(req.tokens(), expected,
                                      err_msg=f"req {req.request_id}")


def _assert_no_page_leaks(srv):
    srv.check_invariants()
    assert srv.live_count == 0
    pool = srv.pool
    # after a full drain, every non-free page is trie-held — anything
    # else is a leaked transfer
    trie_pages = set(pool.prefix.page_counts())
    assert len(pool._free_page_set) + len(trie_pages) == pool.num_pages
    assert not (trie_pages & pool._free_page_set)


# ---------------------------------------------------------------------------
class TestDisaggParity:
    def test_disaggregated_greedy_bitwise_matches_single_engine(self, stack):
        """1-prefill + 1-decode fleet produces EXACTLY the single-engine
        generate() tokens, every request travelling through a page
        transfer; zero post-warmup recompiles with strict watchdogs on
        BOTH replicas."""
        _, _, engine = stack
        router = ReplicaRouter(
            [paged_server(engine, role="prefill", strict_recompile=True),
             paged_server(engine, role="decode", strict_recompile=True)])
        _warm(router)
        prompts = _prompts(7)
        reqs = [router.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, BUDGETS)]
        router.run_until_drained(max_steps=600)
        _assert_bitwise(engine, reqs, prompts, BUDGETS)
        router.check_invariants()
        assert router.recompiles == 0
        assert router.transfers >= len(reqs)
        topo = router.fleet_topology()
        assert topo["counts"] == {"prefill": 1, "decode": 1, "both": 0}
        assert topo["transfers_in_flight"] == 0

    def test_fleet_metrics_and_prometheus_surface(self, stack):
        _, _, engine = stack
        router = ReplicaRouter([paged_server(engine, role="prefill"),
                                paged_server(engine, role="decode")])
        _warm(router)
        prom = router.registry.to_prometheus()
        assert "router_fleet_size 2" in prom
        assert "router_transfers_total" in prom
        assert "router_transfers_in_flight 0" in prom
        st = router.stats()
        assert st["transfers"] == router.transfers > 0
        assert st["transfer_bytes"] == router.transfer_bytes > 0
        assert st["fleet"]["fleet_size"] == 2


# ---------------------------------------------------------------------------
class TestMidTransferDeath:
    def test_decode_replica_dies_mid_transfer(self, stack):
        """A destination replica that dies while seating an imported
        batch: its pages are unwound (no leak in EITHER pool), the
        replica is retired, and the parked request re-homes to the
        surviving decode replica with bitwise-correct output."""
        _, _, engine = stack
        pre = paged_server(engine, role="prefill")
        d0 = paged_server(engine, role="decode")
        d1 = paged_server(engine, role="decode")
        router = ReplicaRouter([pre, d0, d1])
        _warm(router)

        # make d0's next seat_pages blow up mid-transfer (AFTER
        # import_pages has allocated destination pages)
        victim = router.replicas[1]
        real_seat = victim.pool.seat_pages
        state = {"armed": True}

        def dying_seat(*a, **kw):
            if state["armed"]:
                state["armed"] = False
                raise RuntimeError("replica died mid-transfer")
            return real_seat(*a, **kw)

        victim.pool.seat_pages = dying_seat
        prompts = _prompts(11)
        reqs = [router.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, BUDGETS)]
        router.run_until_drained(max_steps=800)
        # the victim was retired by the failed transfer (the parked
        # request never left the source, so it re-homes by retry on the
        # surviving sibling, not through the failover re-admit path)
        assert not router._alive[1]
        # ... every request still finished, bitwise identical
        _assert_bitwise(engine, reqs, prompts, BUDGETS)
        router.check_invariants()
        # no page leaked in either pool: the dead replica's imported
        # pages were unwound, the source's copies released on handoff
        victim.pool.seat_pages = real_seat
        for srv in (pre, d0, d1):
            _assert_no_page_leaks(srv)

    def test_adopt_unwind_leaves_destination_pool_clean(self, stack):
        """Engine-level unwind contract: a seat failure inside adopt()
        hands back the WHOLE imported batch and the slot, leaving the
        destination pool exactly as it was."""
        _, _, engine = stack
        pre = paged_server(engine, role="prefill")
        dec = paged_server(engine, role="decode")
        prompt = _prompts(13)[2]
        req = pre.submit(prompt, max_new_tokens=4)
        for _ in range(40):
            pre.step()
            if req in pre.pending_handoffs():
                break
        assert req in pre.pending_handoffs()
        free_slots = dec.pool.free_count
        free_pages = len(dec.pool._free_page_set)
        real_seat = dec.pool.seat_pages
        dec.pool.seat_pages = lambda *a, **kw: (_ for _ in ()).throw(
            RuntimeError("boom"))
        with pytest.raises(RuntimeError):
            dec.adopt(req, pre)
        dec.pool.seat_pages = real_seat
        assert dec.pool.free_count == free_slots
        assert len(dec.pool._free_page_set) == free_pages
        dec.check_invariants()
        # source still owns the request; a later adopt succeeds
        src_slot = next(s for s, r in pre._slot_req.items() if r is req)
        stats = dec.adopt(req, pre)
        assert stats["pages"] >= 1
        pre.finish_handoff(req, src_slot)
        dec.run_until_drained(max_steps=200)
        assert req.state is RequestState.FINISHED


# ---------------------------------------------------------------------------
class TestElasticFleet:
    def test_add_and_retire_under_load_drops_nothing(self, stack):
        """Scale events racing live traffic: no request is dropped or
        rejected to death, no page leaks, and the watchdogs stay at
        zero recompiles (strict on every replica, including the
        newcomer)."""
        _, _, engine = stack
        router = ReplicaRouter(
            [paged_server(engine, role="prefill", strict_recompile=True),
             paged_server(engine, role="decode", strict_recompile=True)])
        _warm(router)
        spawn = _spawn_factory(engine, strict_recompile=True)
        prompts = _prompts(17)
        # wave 1 in flight ...
        reqs = [router.submit(p, max_new_tokens=b)
                for p, b in zip(prompts[:3], BUDGETS[:3])]
        for _ in range(4):
            router.step()
        # ... scale OUT mid-flight, then submit wave 2
        i = router.add_replica(spawn("decode"), "decode")
        assert router.last_scale_event["action"] == "add"
        reqs += [router.submit(p, max_new_tokens=b)
                 for p, b in zip(prompts[3:], BUDGETS[3:])]
        for _ in range(4):
            router.step()
        # scale IN (drain-then-retire via failover re-homing)
        router.retire_replica(i)
        assert router.last_scale_event["action"] == "retire"
        router.run_until_drained(max_steps=800)
        _assert_bitwise(engine, reqs, prompts, BUDGETS)
        router.check_invariants()
        assert router.recompiles == 0
        assert len(router.scale_events) == 2

    def test_autoscale_spawns_on_sustained_pressure_and_retires_idle(
            self, stack):
        """The burn-rate-driven loop: sustained saturation on a role
        spawns a replica of that role; sustained idleness drains and
        retires it back to the floor."""
        _, _, engine = stack
        router = ReplicaRouter(
            [paged_server(engine, role="prefill"),
             paged_server(engine, role="decode")],
            spawner=_spawn_factory(engine), scale_patience=2)
        _warm(router)
        # saturate the decode role: more live work than its 2 slots
        prompts = _prompts(19) + _prompts(23)
        budgets = BUDGETS + BUDGETS
        reqs = [router.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, budgets)]
        router.run_until_drained(max_steps=1200)
        assert all(r.state is RequestState.FINISHED for r in reqs)
        grew = [e for e in router.scale_events if e["action"] == "add"]
        assert grew, "sustained pressure never triggered a spawn"
        # idle ticks retire the surplus back down
        for _ in range(40):
            router.step()
            if router.num_replicas - len(
                    [e for e in router.scale_events
                     if e["action"] == "retire"]) <= 2:
                break
        shrank = [e for e in router.scale_events if e["action"] == "retire"]
        assert shrank, "sustained idleness never retired the surplus"
        router.check_invariants()

    def test_retire_refuses_to_strand_a_role(self, stack):
        _, _, engine = stack
        router = ReplicaRouter([paged_server(engine, role="prefill"),
                                paged_server(engine, role="decode")])
        with pytest.raises(ValueError):
            router.retire_replica(0)   # last prefill-capable
        with pytest.raises(ValueError):
            router.retire_replica(1)   # last decode-capable
