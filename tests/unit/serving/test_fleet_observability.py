"""Fleet-scope observability: cross-replica request journeys, the
merged telemetry plane, and fleet post-mortems (ISSUE 20).

The load-bearing scenario is the nasty one: a request prefilled on
replica 0, handed off to decode replica 1, which is then killed
MID-STREAM. The journey must still read as ONE story — dispatch,
transfer, failover re-home, finish — stitched across every home it
touched, and the merged Perfetto export must carry one process lane
per replica with flow arrows across the boundaries. Everything here
is host-side bookkeeping: the strict recompile watchdogs stay armed
throughout, pinning the zero-new-jitted-programs acceptance bar.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import RequestState, ServingEngine
from deepspeed_tpu.serving.router import ReplicaRouter
from deepspeed_tpu.telemetry import (FLEET_POST_MORTEM_KEYS,
                                     QuantileDigest, Tracer)

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)
PS = 8

LENGTHS = [5, 9, 12, 5, 17, 12]
BUDGETS = [6, 4, 8, 3, 7, 5]


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


def paged_server(engine, role="both", **kw):
    kw.setdefault("prefill_chunk", PS)
    kw.setdefault("tracer", Tracer())
    kw.setdefault("slo", True)
    kw.setdefault("flight_recorder", True)
    return ServingEngine(engine, num_slots=2, max_queue_depth=32,
                         paged_kv={"page_size": PS, "num_pages": None},
                         role=role, **kw)


def _fleet(engine, roles, **kw):
    kw.setdefault("tracer", Tracer())
    return ReplicaRouter([paged_server(engine, role=r) for r in roles], **kw)


def _prompts(seed):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 64, size=n).astype(np.int32) for n in LENGTHS]


def _warm(router, *, max_steps=600):
    reqs = [router.submit(p, max_new_tokens=b)
            for p, b in zip(_prompts(3), BUDGETS)]
    router.run_until_drained(max_steps=max_steps)
    assert all(r.state is RequestState.FINISHED for r in reqs)
    router.end_warmup()


def _assert_bitwise(engine, reqs, prompts, budgets):
    for req, prompt, budget in zip(reqs, prompts, budgets):
        assert req.state is RequestState.FINISHED, (
            req.request_id, req.state, req.finish_reason)
        expected = engine.generate(np.asarray(prompt)[None],
                                   max_new_tokens=budget)[0]
        np.testing.assert_array_equal(req.tokens(), expected,
                                      err_msg=f"req {req.request_id}")


def _assert_perfetto_schema(doc, *, lanes):
    """Minimal Chrome-trace/Perfetto schema check for a merged fleet
    export: per-replica process lanes, named via metadata, every flow
    terminator carrying ``bp: "e"`` (enclosing-slice binding — without
    it Perfetto drops the arrow)."""
    assert set(doc) >= {"traceEvents", "displayTimeUnit", "otherData"}
    events = doc["traceEvents"]
    assert {e["pid"] for e in events} == set(range(lanes))
    names = {e["pid"]: e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert set(names) == set(range(lanes))
    assert names[0] == "router"
    for ev in events:
        assert ev["ph"] in ("X", "i", "C", "b", "n", "e", "s", "f", "M"), ev
        if ev["ph"] in ("s", "f"):
            assert "id" in ev and "cat" in ev
        if ev["ph"] == "f":
            assert ev.get("bp") == "e", ev
    # flow arrows must actually pair ACROSS lanes (same cat+id, start
    # and finish on different pids), else the hop renders as nothing
    starts = {(e["cat"], e["id"]): e["pid"] for e in events
              if e["ph"] == "s"}
    cross = [e for e in events if e["ph"] == "f"
             and starts.get((e["cat"], e["id"])) not in (None, e["pid"])]
    assert cross, "no cross-lane flow arrow in merged trace"
    return names


# ---------------------------------------------------------------------------
class TestJourneyStitching:
    def test_handoff_then_decode_death_is_one_complete_journey(self, stack):
        """Prefill -> handoff -> decode replica KILLED mid-stream ->
        failover re-home: the stitched journey is ONE complete story
        spanning every home, the output stays bitwise-identical, and
        the merged Perfetto export passes the schema check."""
        _, _, engine = stack
        router = _fleet(engine, ["prefill", "decode", "decode"])
        _warm(router)
        prompts = _prompts(7)
        reqs = [router.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, BUDGETS)]
        # step until a decode replica owns live work, then kill it
        victim = None
        for _ in range(200):
            router.step()
            victim = next((i for i in (1, 2) if router._alive[i]
                           and router.replicas[i].live_count), None)
            if victim is not None:
                break
        assert victim is not None, "no request ever reached a decode home"
        vic = router.replicas[victim]
        real_step = vic.step
        vic.step = lambda: (_ for _ in ()).throw(
            RuntimeError("decode replica killed mid-stream"))
        router.run_until_drained(max_steps=800)
        vic.step = real_step
        assert not router._alive[victim]
        assert router.failovers >= 1
        _assert_bitwise(engine, reqs, prompts, BUDGETS)

        # every journey closed: finished == complete, nothing parked
        js = router.journey_summary()
        assert js["finished"] == js["total"]
        assert js["complete"] == js["finished"], js["incomplete"]

        # at least one journey was re-homed by the failover and its
        # stitched view covers BOTH decode homes plus the prefill home
        rehomed = [router.journey(router.journey_of(r.request_id))
                   for r in reqs]
        multi = [j for j in rehomed
                 if any(h["kind"] == "failover" for h in j["hops"])]
        assert multi, "failover left no journey hop"
        j = multi[0]
        assert j["complete"] and j["terminal"] == "finish"
        assert victim in j["homes"] and len(set(j["homes"])) >= 2
        kinds = [h["kind"] for h in j["hops"]]
        assert kinds[0] == "dispatch" and kinds[-1] == "finish"
        assert "transfer" in kinds and "failover" in kinds
        # hop timestamps interleave with timeline events on ONE clock:
        # the stitched event list is globally sorted
        ts = [e["t_ns"] for e in j["events"]]
        assert ts == sorted(ts)
        # the corpse's lifecycle was closed terminally (failed_over) and
        # the inheritor opened a resumed line — no home left dangling
        evs = [(e["replica"], e["event"]) for e in j["events"]]
        assert any(ev == "failed_over" for _, ev in evs)
        assert any(ev == "resumed" for _, ev in evs)
        router.check_invariants()

    def test_export_trace_merged_perfetto_document(self, stack, tmp_path):
        _, _, engine = stack
        router = _fleet(engine, ["prefill", "decode"])
        _warm(router)
        path = str(tmp_path / "fleet-trace.json")
        n = router.export_trace(path)
        assert n > 0
        doc = json.load(open(path))
        names = _assert_perfetto_schema(doc, lanes=3)
        assert names[1].startswith("replica0") and "prefill" in names[1]
        assert names[2].startswith("replica1") and "decode" in names[2]
        assert doc["otherData"]["processes"]["0"] == "router"
        router.check_invariants()

    def test_parked_mid_handoff_journey_is_not_falsely_complete(self, stack):
        """A request parked in ``pending_handoffs()`` is BETWEEN homes:
        its source timeline is still open AND flagged parked, so the
        stitched journey must read incomplete until a decode replica
        adopts and finishes it."""
        _, _, engine = stack
        router = _fleet(engine, ["prefill", "decode"])
        _warm(router)
        pre = router.replicas[0]
        req = router.submit(_prompts(13)[2], max_new_tokens=4)
        parked = False
        for _ in range(40):
            pre.step()          # step ONLY the prefill replica: the
            #                     router never drains the handoff
            if req in pre.pending_handoffs():
                parked = True
                break
        assert parked
        assert req.request_id in pre.timelines.parked_ids()
        j = router.journey(req.journey_id)
        assert not j["complete"]
        assert j["parked_homes"] == [0]
        assert j["terminal"] is None  # in flight: not finished, so the
        #                               completeness gate ignores it
        # drain through the router: adoption clears the parked flag and
        # the journey closes
        router.run_until_drained(max_steps=400)
        assert req.state is RequestState.FINISHED
        j = router.journey(req.journey_id)
        assert j["complete"] and not j["parked_homes"]
        assert req.request_id not in pre.timelines.parked_ids()
        router.check_invariants()

    def test_journeys_survive_zero_recompile_budget(self, stack):
        """The whole observability plane is host-side: strict watchdogs
        on every replica see ZERO post-warmup compiles with journeys,
        fleet metrics and trace export all active."""
        _, _, engine = stack
        router = ReplicaRouter(
            [paged_server(engine, role="prefill", strict_recompile=True),
             paged_server(engine, role="decode", strict_recompile=True)],
            tracer=Tracer())
        _warm(router)
        prompts = _prompts(29)
        reqs = [router.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, BUDGETS)]
        router.run_until_drained(max_steps=600)
        _assert_bitwise(engine, reqs, prompts, BUDGETS)
        router.fleet.to_prometheus()
        router.fleet.health_summary()
        router.fleet.efficiency_snapshot()
        assert router.recompiles == 0


# ---------------------------------------------------------------------------
class TestFleetTelemetryPlane:
    def test_merged_prometheus_exposition(self, stack):
        _, _, engine = stack
        router = _fleet(engine, ["prefill", "decode"])
        _warm(router)
        prom = router.fleet.to_prometheus()
        # router-scope series stay unlabeled (backward compatible)
        assert "router_fleet_size 2" in prom
        assert "router_transfers_total" in prom
        assert "router_transfer_wire_bytes_total" in prom
        # per-replica series labeled by replica + role
        assert 'replica="0",role="prefill"' in prom
        assert 'replica="1",role="decode"' in prom
        # fleet rollups
        for series in ("fleet_goodput", "fleet_burn_short",
                       "fleet_journeys_total", "fleet_journeys_complete",
                       "fleet_transfer_latency_p99_ms"):
            assert series in prom, series
        # exactly one TYPE line per metric family, even with one series
        # per replica (Prometheus text format rejects duplicates)
        type_lines = [ln for ln in prom.splitlines()
                      if ln.startswith("# TYPE ")]
        assert len(type_lines) == len({ln.split()[2] for ln in type_lines})

    def test_transfer_wire_bytes_and_latency_metrics(self, stack):
        """Satellite (a): every page transfer feeds the wire-bytes
        counter + histogram and the transfer-latency digest; trie-hit
        pages never cross the wire so the counter equals the router's
        ``transfer_bytes`` (which already excludes them)."""
        _, _, engine = stack
        router = _fleet(engine, ["prefill", "decode"])
        _warm(router)
        prompts = _prompts(7)
        reqs = [router.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, BUDGETS)]
        router.run_until_drained(max_steps=600)
        assert all(r.state is RequestState.FINISHED for r in reqs)
        assert router.transfers >= len(reqs)
        assert router.transfer_latency.count == router.transfers
        p99 = router.transfer_latency.quantile(0.99)
        assert p99 > 0
        snap = router.registry.snapshot()
        assert snap["router/transfer_wire_bytes_total"] == \
            router.transfer_bytes > 0
        assert snap["router/transfer_wire_bytes/count"] == router.transfers
        assert snap["router/transfer_wire_bytes/sum"] == \
            router.transfer_bytes
        eff = router.fleet.efficiency_snapshot()
        assert eff["transfer_latency_p99_ms"] == pytest.approx(p99)

    def test_fleet_goodput_sums_windows_not_burns(self, stack):
        """Fleet goodput must equal what ONE tracker that saw every
        request would report — sum the raw [admitted, good] window
        pairs across replicas, never average per-replica ratios
        (2/10 + 8/8 averaged is 0.6; pooled it is 10/18)."""
        _, _, engine = stack
        router = _fleet(engine, ["prefill", "decode"])
        a, b = router.replicas[0].slo, router.replicas[1].slo
        for _ in range(10):
            a.observe_admitted()
        for _ in range(2):
            a.observe_finish(ttft_s=0.01, e2e_s=0.01)
        for _ in range(8):
            b.observe_admitted()
            b.observe_finish(ttft_s=0.01, e2e_s=0.01)
        g = router.fleet.goodput()
        assert g["admitted"] == 18 and g["good"] == 10
        assert g["goodput_slo"] == pytest.approx(10 / 18)
        assert g["alert_state"] in ("ok", "warn", "page")

    def test_quantile_merge_accuracy_pinned(self):
        """Satellite/acceptance: merging N per-replica digests is as
        accurate as one digest that saw every sample, and both land
        within the digest's relative-error bound of the exact numpy
        percentile."""
        rng = np.random.default_rng(42)
        samples = rng.lognormal(mean=3.0, sigma=1.2, size=8000)
        shards = np.array_split(samples, 4)
        digests = []
        for shard in shards:
            d = QuantileDigest()
            for v in shard:
                d.add(float(v))
            digests.append(d)
        merged = QuantileDigest()
        for d in digests:
            merged = merged.merge(d)
        one = QuantileDigest()
        for v in samples:
            one.add(float(v))
        assert merged.count == one.count == len(samples)
        for q in (0.5, 0.9, 0.99):
            exact = float(np.percentile(samples, q * 100))
            got = merged.quantile(q)
            # merged == single-digest (bucketwise merge is lossless)
            assert got == pytest.approx(one.quantile(q))
            assert abs(got - exact) <= 2 * merged.rel_error * exact, (
                q, got, exact)

    def test_digest_param_mismatch_raises(self):
        a, b = QuantileDigest(), QuantileDigest(rel_error=0.05)
        with pytest.raises(ValueError):
            a.merge(b)

    def test_health_summary_per_replica_and_per_role(self, stack):
        _, _, engine = stack
        router = _fleet(engine, ["prefill", "decode"])
        _warm(router)
        hs = router.fleet.health_summary()
        assert set(hs["replicas"]) == {"0", "1"}
        assert hs["replicas"]["0"]["role"] == "prefill"
        assert hs["replicas"]["1"]["alert"] in ("ok", "warn", "page")
        assert set(hs["roles"]) == {"prefill", "decode"}
        for role in hs["roles"].values():
            assert {"replicas", "queue_depth", "backlog"} <= set(role)
        assert hs["journeys"]["complete"] == hs["journeys"]["finished"]
        assert hs["alert_state"] in ("ok", "warn", "page")


# ---------------------------------------------------------------------------
class TestFleetPostMortem:
    def test_replica_death_dumps_one_fleet_scoped_file(self, stack,
                                                       tmp_path):
        """ANY replica failing mid-step produces ONE fleet post-mortem:
        every replica's flight-recorder ring, the router's dispatch and
        scale-event log, journeys, and the trigger replica marked."""
        _, _, engine = stack
        router = _fleet(engine, ["prefill", "decode", "decode"],
                        dump_dir=str(tmp_path))
        _warm(router)
        prompts = _prompts(7)
        reqs = [router.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, BUDGETS)]
        victim = None
        for _ in range(200):
            router.step()
            victim = next((i for i in (1, 2) if router._alive[i]
                           and router.replicas[i].live_count), None)
            if victim is not None:
                break
        assert victim is not None
        vic = router.replicas[victim]
        real_step = vic.step
        vic.step = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
        router.run_until_drained(max_steps=800)
        vic.step = real_step
        assert all(r.state is RequestState.FINISHED for r in reqs)

        files = [f for f in os.listdir(tmp_path)
                 if f.startswith("fleet-postmortem")]
        assert len(files) == 1, files
        assert "replica_error" in files[0]
        pm = json.load(open(tmp_path / files[0]))
        # the key set is the fleet debugging contract — pinned
        assert set(pm) == set(FLEET_POST_MORTEM_KEYS)
        assert pm["trigger_replica"] == victim
        assert pm["fleet_size"] == 3
        assert set(pm["replicas"]) == {"0", "1", "2"}
        assert pm["replicas"][str(victim)]["trigger"] is True
        assert sum(r["trigger"] for r in pm["replicas"].values()) == 1
        # per-replica rings share the injected clock: step records carry
        # router-clock "t" stamps so the dump aligns without guesswork
        for rep in pm["replicas"].values():
            assert {"schema_version", "steps", "registry",
                    "role", "alive"} <= set(rep)
        steps = [s for rep in pm["replicas"].values()
                 for s in rep["steps"]]
        assert steps and all("t" in s and "replica" in s for s in steps)
        assert pm["router"]["failovers"] >= 0
        assert pm["journeys"]
        assert len(router.fleet.dumps) == 1

    def test_invariant_violation_dumps_with_trigger(self, stack, tmp_path):
        _, _, engine = stack
        router = _fleet(engine, ["prefill", "decode"],
                        dump_dir=str(tmp_path))
        _warm(router)
        # corrupt replica 1's slot bookkeeping so its own invariant
        # audit trips inside router.check_invariants()
        from deepspeed_tpu.serving import Request
        from deepspeed_tpu.serving.resilience import InvariantViolation
        ghost = Request(999, np.zeros(4, np.int32), 4)
        router.replicas[1]._slot_req[99] = ghost
        with pytest.raises(InvariantViolation):
            router.check_invariants()
        del router.replicas[1]._slot_req[99]
        files = [f for f in os.listdir(tmp_path)
                 if f.startswith("fleet-postmortem")]
        assert len(files) == 1
        pm = json.load(open(tmp_path / files[0]))
        assert pm["trigger_replica"] == 1
        assert pm["replicas"]["1"]["trigger"] is True

    def test_dump_never_raises_without_dump_dir(self, stack):
        _, _, engine = stack
        router = _fleet(engine, ["prefill", "decode"])
        assert router.fleet.dump("replica_error") is None
        assert router.fleet.dumps == []
