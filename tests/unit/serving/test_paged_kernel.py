"""Paged-kernel serving tests (ISSUE 13): the fused Pallas paged-attention
path (``paged_kv={"kernel": "on"}``) must be a pure EXECUTABLE change —
greedy tokens bitwise-match the dense gather/scatter oracle (``"off"``)
and whole-batch ``generate()`` under slot churn, speculative rollback,
and preempt/resume; the kernel knob is validated and backend-gated; page
churn through the kernel never recompiles after warmup."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import TransformerConfig, TransformerLM
from deepspeed_tpu.serving import PagedKVPool, RequestState, ServingEngine

TINY = dict(vocab_size=64, max_seq_len=64, n_embd=32, n_layer=2, n_head=4,
            dtype=jnp.float32)
PS = 8  # page size == prefill chunk for every server in this file


@pytest.fixture(scope="module")
def stack():
    cfg = TransformerConfig(**TINY)
    model = TransformerLM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 64)
    params = model.init({"params": jax.random.PRNGKey(1)}, ids,
                        method=model.logits)["params"]
    engine = ds.init_inference(model=model, model_parameters=params,
                               config={"dtype": "float32"})
    return model, params, engine


def kernel_server(engine, kernel="on", num_slots=2, **kw):
    kw.setdefault("prefill_chunk", PS)
    return ServingEngine(engine, num_slots=num_slots, max_queue_depth=32,
                         paged_kv={"page_size": PS, "kernel": kernel}, **kw)


def run_traffic(srv, prompts, budgets, max_steps=400):
    reqs = [srv.submit(p, max_new_tokens=b) for p, b in zip(prompts, budgets)]
    srv.run_until_drained(max_steps=max_steps)
    srv.check_invariants()
    return reqs


def _mixed_workload(seed=7, n=6):
    rng = np.random.default_rng(seed)
    lengths = rng.integers(3, 22, size=n)
    prompts = [rng.integers(0, 64, size=int(T)).astype(np.int32)
               for T in lengths]
    budgets = [int(b) for b in rng.integers(3, 9, size=n)]
    return prompts, budgets


# ---------------------------------------------------------------------------
# knob + gating


def test_kernel_knob_validates_and_gates(stack):
    _, _, engine = stack
    srv_on = kernel_server(engine, "on")
    assert isinstance(srv_on.pool, PagedKVPool)
    assert srv_on.pool.kernel_active
    assert srv_on.pool._paged_decode_kernel_jit is not None
    srv_off = kernel_server(engine, "off")
    assert not srv_off.pool.kernel_active
    assert srv_off.pool._paged_decode_kernel_jit is None
    # "auto" follows the backend: kernel only on real TPU hardware
    srv_auto = kernel_server(engine, "auto")
    expect = jax.default_backend() == "tpu"
    assert srv_auto.pool.kernel_active == expect
    with pytest.raises(ValueError, match="kernel"):
        kernel_server(engine, "sometimes")


def test_max_query_rows_drift_guard(stack, monkeypatch):
    """The pool mirrors the kernel's row budget as a local literal (so
    graftcheck can decide the verify gate statically); binding must
    refuse to run if the two ever drift."""
    import deepspeed_tpu.serving.paged_pool as pp

    _, _, engine = stack
    monkeypatch.setattr(pp, "_KERNEL_MAX_QUERY_ROWS", 4)
    with pytest.raises(RuntimeError, match="MAX_QUERY_ROWS"):
        kernel_server(engine, "on")


# ---------------------------------------------------------------------------
# bitwise parity


def test_kernel_tokens_bitwise_match_dense_and_generate(stack):
    """Multi-wave slot churn through the fused kernel: per-request tokens
    must equal the dense-oracle server's AND static-batch generate()'s,
    bit for bit (greedy)."""
    _, _, engine = stack
    prompts, budgets = _mixed_workload()
    on = run_traffic(kernel_server(engine, "on"), prompts, budgets)
    off = run_traffic(kernel_server(engine, "off"), prompts, budgets)
    for a, b, p, budget in zip(on, off, prompts, budgets):
        assert a.state == RequestState.FINISHED, a.finish_reason
        np.testing.assert_array_equal(a.tokens(), b.tokens())
        expected = engine.generate(np.asarray(p)[None],
                                   max_new_tokens=budget)[0]
        np.testing.assert_array_equal(a.tokens(), expected)


def test_kernel_spec_verify_parity_with_rollback(stack):
    """Speculative decoding through the fused verify kernel: repetitive
    prompts drive acceptances (multi-row verify widths), random ones
    drive rejections (rollback across page boundaries); tokens must
    bitwise-match the dense verify path either way."""
    _, _, engine = stack
    rng = np.random.default_rng(3)
    motif = rng.integers(0, 64, size=5)
    prompts = [np.tile(motif, 4).astype(np.int32),          # acceptances
               rng.integers(0, 64, size=17).astype(np.int32),  # rejections
               np.tile(motif, 3)[:-2].astype(np.int32)]
    budgets = [8, 6, 9]
    spec = {"k": 3, "drafter": "ngram"}

    def run(kernel):
        srv = kernel_server(engine, kernel, spec_decode=dict(spec))
        return srv, run_traffic(srv, prompts, budgets)

    srv_on, on = run("on")
    assert srv_on.pool._paged_verify_kernel_jit is not None
    _, off = run("off")
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a.tokens(), b.tokens())
    s = srv_on.stats()
    assert s["spec_drafted"] > 0 and s["spec_accepted"] > 0


def test_verify_width_beyond_row_budget_falls_back(stack):
    """spec_k + 1 rows past MAX_QUERY_ROWS must fall back to the dense
    verify composition (the kernel's row budget is the sublane count) —
    with identical tokens, not an error."""
    from deepspeed_tpu.ops.attention.paged_attention import MAX_QUERY_ROWS

    _, _, engine = stack
    k = MAX_QUERY_ROWS  # verify width k+1 exceeds the kernel budget
    rng = np.random.default_rng(5)
    motif = rng.integers(0, 64, size=4)
    prompts = [np.tile(motif, 5).astype(np.int32)]
    budgets = [10]
    spec = {"k": k, "drafter": "ngram"}
    srv_on = kernel_server(engine, "on", spec_decode=dict(spec))
    assert srv_on.pool._paged_verify_kernel_jit is not None
    on = run_traffic(srv_on, prompts, budgets)
    off = run_traffic(kernel_server(engine, "off",
                                    spec_decode=dict(spec)),
                      prompts, budgets)
    np.testing.assert_array_equal(on[0].tokens(), off[0].tokens())


def test_kernel_preempt_resume_parity(stack):
    """Preempt mid-decode, resume through the kernel arm: the rebuilt
    page table must feed the kernel exactly the tokens the dense arm
    (and an unpreempted generate()) sees."""
    _, _, engine = stack
    rng = np.random.default_rng(11)
    prompt = rng.integers(0, 64, size=18).astype(np.int32)

    def run(kernel):
        srv = kernel_server(engine, kernel, num_slots=2)
        req = srv.submit(prompt, max_new_tokens=12)
        for _ in range(4):                       # partway through decode
            srv.step()
        srv.preempt(req.request_id)
        assert req.preemptions == 1
        srv.run_until_drained(max_steps=200)
        srv.check_invariants()
        return req

    a, b = run("on"), run("off")
    assert a.state == RequestState.FINISHED
    np.testing.assert_array_equal(a.tokens(), b.tokens())
    expected = engine.generate(np.asarray(prompt)[None],
                               max_new_tokens=12)[0]
    np.testing.assert_array_equal(a.tokens(), expected)


# ---------------------------------------------------------------------------
# zero-recompile churn


def test_kernel_churn_never_recompiles_after_warmup(stack):
    """A warm replay of the whole workload (slot churn, prefix hits,
    every admission grouping it uses) through the kernel server must not
    grow any executable cache."""
    _, _, engine = stack
    prompts, budgets = _mixed_workload(seed=13, n=6)
    srv = kernel_server(engine, "on")
    run_traffic(srv, prompts, budgets)
    srv.end_warmup()
    run_traffic(srv, prompts, budgets)
    assert srv.watchdog.recompiles == 0
    manifest = srv.watchdog.signature_manifest()
    assert "SlotPool._paged_decode_kernel_jit" in manifest
