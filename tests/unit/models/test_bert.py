"""BERT family — analog of the reference's BERT-layer equivalence and
pretraining tests (tests/unit/ops/accelerators/test_accelerator_forward.py
compares against the HF BERT layer; here numerics are checked against a
plain jnp attention reference and training drives the engine)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.bert import (
    BertConfig,
    BertForPreTraining,
    BertModel,
    bert_config,
)


def _tiny_cfg(**kw):
    return BertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=2, intermediate_size=64,
                      max_position_embeddings=32,
                      hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0, **kw)


def test_presets():
    assert bert_config("bert-large").num_hidden_layers == 24
    db = bert_config("distil-bert")
    assert db.num_hidden_layers == 6 and not db.use_pooler


def test_encoder_shapes_and_pooler():
    cfg = _tiny_cfg()
    model = BertModel(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    seq, pooled = model.apply(params, ids)
    assert seq.shape == (2, 16, 32)
    assert pooled.shape == (2, 32)


def test_attention_mask_blocks_padding():
    """Changing PADDED tokens must not change unpadded outputs."""
    cfg = _tiny_cfg()
    model = BertModel(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, (1, 8)).astype(np.int32)
    mask = np.asarray([[1, 1, 1, 1, 0, 0, 0, 0]], np.int32)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(ids))
    seq1, _ = model.apply(params, jnp.asarray(ids),
                          attention_mask=jnp.asarray(mask))
    ids2 = ids.copy()
    ids2[0, 5:] = (ids2[0, 5:] + 7) % 64  # change padding tokens
    seq2, _ = model.apply(params, jnp.asarray(ids2),
                          attention_mask=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(seq1[0, :4]),
                               np.asarray(seq2[0, :4]), rtol=1e-4,
                               atol=1e-5)


def test_pretraining_loss_decreases():
    cfg = _tiny_cfg()
    model = BertForPreTraining(cfg)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    B = engine.train_batch_size()
    ids = rng.integers(0, 64, (B, 16)).astype(np.int32)
    labels = np.where(rng.random((B, 16)) < 0.15, ids, -100).astype(np.int32)
    batch = {"input_ids": ids, "mlm_labels": labels,
             "attention_mask": np.ones((B, 16), np.int32),
             "next_sentence_label": rng.integers(0, 2, (B,)).astype(np.int32)}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_distilbert_no_token_type():
    cfg = _tiny_cfg(use_token_type=False, use_pooler=False)
    model = BertModel(cfg)
    ids = jnp.ones((2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    seq, pooled = model.apply(params, ids)
    assert pooled is None
    assert "token_type_embeddings" not in params["params"]
