"""Flash attention in the unified TransformerLM (full-context forward).

The per-family model exposes ``use_flash_attention`` like GPT2LMHeadModel:
``auto`` turns the Pallas flash kernel on from the tuned crossover length
on TPU; ``True`` forces it (interpret mode here, numerics only). The
streamed param-offload training path and long-context training depend on
this: the einsum formulation materializes the (B, H, T, T) logits tensor,
flash (and its custom_vjp) keeps attention memory O(T).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.transformer_lm import (
    TransformerLM,
    transformer_config,
)

_TINY = dict(vocab_size=64, n_embd=32, n_layer=1, n_head=2,
             max_seq_len=32, dtype=jnp.float32)


def _loss(model, params, ids):
    return model.apply({"params": params}, {"input_ids": ids},
                       deterministic=True)


def test_flash_forward_and_grads_match_einsum():
    """Forced flash tracks the einsum path for loss AND parameter grads,
    including grouped-query attention (kv heads repeated for the kernel)."""
    cfg_e = transformer_config("llama", n_kv_head=1,
                               use_flash_attention=False, **_TINY)
    cfg_f = transformer_config("llama", n_kv_head=1,
                               use_flash_attention=True, **_TINY)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 32)))
    m_e, m_f = TransformerLM(cfg_e), TransformerLM(cfg_f)
    params = m_e.init({"params": jax.random.PRNGKey(0)}, ids,
                      method=m_e.logits)["params"]

    l_e = float(_loss(m_e, params, ids))
    l_f = float(_loss(m_f, params, ids))
    assert abs(l_e - l_f) < 5e-3, (l_e, l_f)

    g_e = jax.grad(lambda p: _loss(m_e, p, ids))(params)
    g_f = jax.grad(lambda p: _loss(m_f, p, ids))(params)
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a - b).max()), g_e, g_f)
    assert max(jax.tree_util.tree_leaves(diffs)) < 5e-3


def test_flash_rejects_alibi_and_train_dropout():
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 64, (1, 32)))
    cfg = transformer_config("bloom", use_flash_attention=True, **_TINY)
    m = TransformerLM(cfg)
    with pytest.raises(ValueError, match="alibi"):
        m.init({"params": jax.random.PRNGKey(0)}, ids, method=m.logits)

    cfg = transformer_config("gpt2", use_flash_attention=True,
                             **{**_TINY, "dropout": 0.1})
    m = TransformerLM(cfg)
    with pytest.raises(ValueError, match="dropout"):
        m.init({"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
               {"input_ids": ids}, deterministic=False)


def test_flash_auto_off_on_cpu():
    """auto mode keeps the einsum path off-TPU (no interpret-mode crawl)."""
    cfg = transformer_config("gpt2", **_TINY)  # auto is the default
    ids = jnp.asarray(np.random.default_rng(2).integers(0, 64, (1, 32)))
    m = TransformerLM(cfg)
    params = m.init({"params": jax.random.PRNGKey(0)}, ids,
                    method=m.logits)["params"]
    assert np.isfinite(float(_loss(m, params, ids)))
