"""Diffusion family tests — clip/unet/vae (the last reference injection
families, module_inject/containers/{clip,unet,vae}.py) + spatial ops."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.diffusion import (
    AutoencoderVAE,
    CLIPConfig,
    CLIPTextEncoder,
    UNet2DCondition,
    UNetConfig,
    VAEConfig,
    diffusion_sharding_rules,
    timestep_embedding,
)
from deepspeed_tpu.ops.spatial import (
    nhwc_bias_add,
    nhwc_bias_add_add,
    nhwc_bias_add_bias_add,
)


def test_spatial_ops_match_manual():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 4, 4, 8)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((2, 4, 4, 8)), jnp.float32)
    b = jnp.asarray(rng.standard_normal(8), jnp.float32)
    b2 = jnp.asarray(rng.standard_normal(8), jnp.float32)
    np.testing.assert_allclose(nhwc_bias_add(x, b), x + b[None, None, None])
    np.testing.assert_allclose(nhwc_bias_add_add(x, b, y),
                               x + b[None, None, None] + y)
    np.testing.assert_allclose(
        nhwc_bias_add_bias_add(x, b, y, b2),
        x + b[None, None, None] + y + b2[None, None, None], atol=1e-6)


def test_timestep_embedding_properties():
    emb = timestep_embedding(jnp.asarray([0, 10, 500]), 64)
    assert emb.shape == (3, 64)
    # t=0 embeds to cos=1, sin=0 halves
    np.testing.assert_allclose(emb[0, :32], np.ones(32), atol=1e-6)
    np.testing.assert_allclose(emb[0, 32:], np.zeros(32), atol=1e-6)
    assert not np.allclose(emb[1], emb[2])


@pytest.fixture
def clip_cfg():
    return CLIPConfig(vocab_size=64, max_positions=16, width=32, layers=2,
                      heads=2)


def test_clip_text_encoder(clip_cfg):
    model = CLIPTextEncoder(clip_cfg)
    ids = np.arange(8, dtype=np.int32)[None].repeat(2, 0) % 64
    params = model.init(jax.random.PRNGKey(0), ids)
    out = jax.jit(lambda p, i: model.apply(p, i))(params, ids)
    assert out.shape == (2, 8, 32)
    assert np.isfinite(np.asarray(out)).all()


def test_unet_denoise_step(clip_cfg):
    ucfg = UNetConfig(in_channels=4, out_channels=4, block_channels=(16, 32),
                      attention_heads=2, cross_attention_dim=32,
                      norm_groups=4)
    unet = UNet2DCondition(ucfg)
    latents = jnp.asarray(np.random.default_rng(0)
                          .standard_normal((2, 8, 8, 4)), jnp.float32)
    t = jnp.asarray([1, 500])
    context = jnp.asarray(np.random.default_rng(1)
                          .standard_normal((2, 8, 32)), jnp.float32)
    params = unet.init(jax.random.PRNGKey(0), latents, t, context)
    out = jax.jit(lambda p, l, tt, c: unet.apply(p, l, tt, c))(
        params, latents, t, context)
    assert out.shape == latents.shape
    assert np.isfinite(np.asarray(out)).all()
    # conditioning matters: different context -> different noise prediction
    out2 = unet.apply(params, latents, t, context + 1.0)
    assert not np.allclose(np.asarray(out), np.asarray(out2))


def test_vae_roundtrip_shapes():
    vae = AutoencoderVAE(VAEConfig(base_channels=16, norm_groups=4))
    images = jnp.asarray(np.random.default_rng(0)
                         .standard_normal((2, 16, 16, 3)), jnp.float32)
    params = vae.init(jax.random.PRNGKey(0), images)
    recon, mean, logvar = vae.apply(params, images)
    assert recon.shape == images.shape
    assert mean.shape == (2, 4, 4, 4)  # 4x spatial reduction, 4 latents
    # encode/decode entry points (the DSVAE surface): encode gives the RAW
    # distribution; scaling applies to the sampled latent before decode
    m, lv = vae.apply(params, images, method=AutoencoderVAE.encode)
    img = vae.apply(params, m * vae.cfg.scaling_factor,
                    method=AutoencoderVAE.decode)
    assert img.shape == images.shape
    np.testing.assert_allclose(np.asarray(img), np.asarray(recon), atol=1e-5)


def test_diffusion_sharding_rules_match_params(clip_cfg):
    import re

    model = CLIPTextEncoder(clip_cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    rules = diffusion_sharding_rules()
    hits = set()
    for kp, _ in jax.tree_util.tree_leaves_with_path(params):
        path = "/".join(str(getattr(k, "key", k)) for k in kp)
        for pat, _spec in rules:
            if re.search(pat, path):
                hits.add(pat)
    # qkv + fc1 col-parallel and out_proj + fc2 row-parallel all match
    assert len(hits) == len(rules), (hits, rules)


def test_latent_denoise_pipeline_compiles(clip_cfg):
    """CLIP conditioning -> UNet denoise -> VAE decode, one jit program
    (the CUDA-graph analog for the stable-diffusion serving path)."""
    ucfg = UNetConfig(block_channels=(16,), attention_heads=2,
                      cross_attention_dim=32, norm_groups=4)
    clip = CLIPTextEncoder(clip_cfg)
    unet = UNet2DCondition(ucfg)
    vae = AutoencoderVAE(VAEConfig(base_channels=16, norm_groups=4))

    ids = np.arange(8, dtype=np.int32)[None] % 64
    latents = jnp.asarray(np.random.default_rng(0)
                          .standard_normal((1, 4, 4, 4)), jnp.float32)
    p_clip = clip.init(jax.random.PRNGKey(0), ids)
    p_unet = unet.init(jax.random.PRNGKey(1), latents,
                       jnp.asarray([1]), jnp.zeros((1, 8, 32)))
    p_vae = vae.init(jax.random.PRNGKey(2),
                     jnp.zeros((1, 16, 16, 3)))

    @jax.jit
    def denoise_step(latents, ids):
        context = clip.apply(p_clip, ids)
        noise = unet.apply(p_unet, latents, jnp.asarray([10]), context)
        latents = latents - 0.1 * noise
        return vae.apply(p_vae, latents, method=AutoencoderVAE.decode)

    img = denoise_step(latents, ids)
    assert img.shape == (1, 16, 16, 3)
    assert np.isfinite(np.asarray(img)).all()
