"""KV-cache container spec: the int32 sublane packing (4 head-dim rows
per word) only exists for head_dim % 4 == 0 — explicit opt-in must fail
loudly, auto mode must fall back to the plain int8 container with a
one-time warning."""

import dataclasses

import jax.numpy as jnp
import pytest

from deepspeed_tpu.models.transformer_lm import (
    _PACK_DISABLED_WARNED,
    TransformerConfig,
    kv_cache_spec,
)

# n_embd=30 / n_head=2 -> head_dim=15, not a multiple of 4
ODD = dict(vocab_size=64, max_seq_len=16, n_embd=30, n_layer=1, n_head=2,
           dtype=jnp.float32, kv_cache_quant=True)


def test_packed_explicit_raises_on_odd_head_dim():
    cfg = TransformerConfig(**ODD, kv_cache_packed=True)
    with pytest.raises(ValueError, match="head_dim % 4"):
        kv_cache_spec(cfg)


def test_packed_auto_falls_back_with_one_warning():
    cfg = TransformerConfig(**ODD, kv_cache_packed=None)
    _PACK_DISABLED_WARNED.discard(cfg.head_dim)
    dtype, cache_d, packed = kv_cache_spec(cfg)
    assert (dtype, cache_d, packed) == (jnp.int8, 15, False)
    assert cfg.head_dim in _PACK_DISABLED_WARNED  # warned this call...
    dtype2, _, _ = kv_cache_spec(cfg)  # ...and only once (set-gated)
    assert dtype2 == jnp.int8


def test_packed_auto_engages_on_aligned_head_dim():
    cfg = TransformerConfig(**{**ODD, "n_embd": 32},  # head_dim 16
                            kv_cache_packed=None)
    dtype, cache_d, packed = kv_cache_spec(cfg)
    assert packed and dtype == jnp.int32 and cache_d == 4

    off = dataclasses.replace(cfg, kv_cache_packed=False)
    dtype, cache_d, packed = kv_cache_spec(off)
    assert (dtype, cache_d, packed) == (jnp.int8, 16, False)
