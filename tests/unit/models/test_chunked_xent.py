"""Streaming cross-entropy parity: loss_chunk must change memory, not
math — same loss and same gradients as the dense (B, T, V)-logits path,
on both model families and both head types."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _loss_and_grads(model, params, batch):
    def f(p):
        return model.apply({"params": p}, batch)

    loss, grads = jax.value_and_grad(f)(params)
    return float(loss), grads


def _assert_tree_close(a, b, rtol, atol):
    for (pa, la), (pb, lb) in zip(
            jax.tree_util.tree_leaves_with_path(a),
            jax.tree_util.tree_leaves_with_path(b)):
        np.testing.assert_allclose(
            np.asarray(la, np.float32), np.asarray(lb, np.float32),
            rtol=rtol, atol=atol, err_msg=str(pa))


@pytest.mark.parametrize("chunk", [5, 16, 64])
def test_gpt2_chunked_matches_dense(chunk):
    import dataclasses

    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config(vocab_size=97, n_positions=16, n_embd=32, n_layer=2,
                     n_head=2, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 97, (3, 16)).astype(np.int32)
    labels = ids.copy()
    labels[0, -3:] = -100  # masked tail
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}

    dense = GPT2LMHeadModel(cfg)
    params = dense.init({"params": jax.random.PRNGKey(0)}, batch)["params"]
    l_dense, g_dense = _loss_and_grads(dense, params, batch)

    chunked = GPT2LMHeadModel(dataclasses.replace(cfg, loss_chunk=chunk))
    l_chunk, g_chunk = _loss_and_grads(chunked, params, batch)

    assert abs(l_dense - l_chunk) < 1e-5 * max(1.0, abs(l_dense))
    _assert_tree_close(g_dense, g_chunk, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("tied", [True, False])
def test_transformer_lm_chunked_matches_dense(tied):
    import dataclasses

    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )

    cfg = TransformerConfig(vocab_size=97, max_seq_len=16, n_embd=32,
                            n_layer=2, n_head=2, dtype=jnp.float32,
                            tie_word_embeddings=tied)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 97, (2, 16)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids)}

    dense = TransformerLM(cfg)
    params = dense.init({"params": jax.random.PRNGKey(0)}, batch)["params"]
    l_dense, g_dense = _loss_and_grads(dense, params, batch)

    chunked = TransformerLM(dataclasses.replace(cfg, loss_chunk=7))
    # from-scratch init of the CHUNKED model must create the full param
    # tree (incl. the untied lm_head the streaming path reads without
    # calling) — same structure as the dense init
    params_c = chunked.init({"params": jax.random.PRNGKey(0)},
                            batch)["params"]
    assert (jax.tree_util.tree_structure(params_c)
            == jax.tree_util.tree_structure(params))
    l_chunk, g_chunk = _loss_and_grads(chunked, params, batch)

    assert abs(l_dense - l_chunk) < 1e-5 * max(1.0, abs(l_dense))
    _assert_tree_close(g_dense, g_chunk, rtol=2e-4, atol=2e-5)


def test_chunked_int8_guard_is_untied_only():
    """loss_chunk + int8-quantized head: the ValueError must fire ONLY
    for an UNTIED int8 lm_head (QuantDense kernel the streaming loss
    can't read); tied embeddings are never quantized and must pass."""
    import dataclasses

    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )

    base = TransformerConfig(vocab_size=64, max_seq_len=16, n_embd=32,
                             n_layer=1, n_head=2, dtype=jnp.float32,
                             loss_chunk=8, int8_weights=True, int8_head=True)
    rng = np.random.default_rng(2)
    batch = {"input_ids": jnp.asarray(
        rng.integers(0, 64, (2, 16)).astype(np.int32))}

    tied = TransformerLM(dataclasses.replace(base, tie_word_embeddings=True))
    params = tied.init({"params": jax.random.PRNGKey(0)}, batch)["params"]
    loss = tied.apply({"params": params}, batch)
    assert np.isfinite(float(loss))

    untied = TransformerLM(dataclasses.replace(base,
                                               tie_word_embeddings=False))
    with pytest.raises(ValueError, match="untied"):
        untied.init({"params": jax.random.PRNGKey(0)}, batch)


def test_chunked_xent_engine_trains():
    """The streaming loss composes with the full engine step (compiled
    train_batch, ZeRO-2): loss decreases."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2,
                     n_head=2, loss_chunk=8)
    eng, _, _, _ = ds.initialize(model=GPT2LMHeadModel(cfg), config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "zero_optimization": {"stage": 2}, "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, 64, (eng.train_batch_size(), 32)).astype(np.int32)}
    losses = [float(eng.train_batch(batch=batch)) for _ in range(4)]
    assert losses[-1] < losses[0]
