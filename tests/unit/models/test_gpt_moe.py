"""MoE-GPT family + DeepSpeedTransformerLayer — analogs of reference
megatron_gpt_moe container and ops/transformer kernel tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.gpt_moe import GPTMoEConfig, GPTMoEModel
from deepspeed_tpu.parallel import initialize_mesh
from deepspeed_tpu.parallel import mesh as mesh_mod


def _tiny(**kw):
    base = dict(vocab_size=64, n_positions=32, n_embd=32,
                n_layer=4, n_head=2, num_experts=4,
                drop_tokens=False, capacity_factor=2.0)
    base.update(kw)
    return GPTMoEConfig(**base)


def test_moe_gpt_trains():
    model = GPTMoEModel(_tiny())
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    b = {"input_ids": rng.integers(
        0, 64, (engine.train_batch_size(), 16)).astype(np.int32)}
    losses = [float(engine.train_batch(batch=b)) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_moe_blocks_alternate():
    model = GPTMoEModel(_tiny(moe_every=2))
    b = {"input_ids": jnp.ones((2, 8), jnp.int32)}
    params = model.init({"params": jax.random.PRNGKey(0),
                         "gating": jax.random.PRNGKey(1)}, b)["params"]
    # blocks 1 and 3 are MoE, 0 and 2 dense
    assert "moe" in params["block_1"] and "moe" in params["block_3"]
    assert "mlp_fc" in params["block_0"] and "mlp_fc" in params["block_2"]


def test_pyramid_experts():
    model = GPTMoEModel(_tiny(num_experts=[2, 4]))
    b = {"input_ids": jnp.ones((2, 8), jnp.int32)}
    params = model.init({"params": jax.random.PRNGKey(0),
                         "gating": jax.random.PRNGKey(1)}, b)["params"]
    g1 = params["block_1"]["moe"]["gate"]["kernel"]
    g3 = params["block_3"]["moe"]["gate"]["kernel"]
    assert g1.shape[-1] == 2 and g3.shape[-1] == 4


def test_moe_gpt_expert_parallel_mesh():
    mesh_mod.reset_mesh()
    mesh = initialize_mesh(data=2, expert=4)
    model = GPTMoEModel(_tiny())
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, mesh=mesh)
    rng = np.random.default_rng(0)
    b = {"input_ids": rng.integers(
        0, 64, (engine.train_batch_size(), 16)).astype(np.int32)}
    l0 = float(engine.train_batch(batch=b))
    l1 = float(engine.train_batch(batch=b))
    assert np.isfinite(l0) and np.isfinite(l1)


class TestDeepSpeedTransformerLayer:
    def test_forward_shapes_both_orderings(self):
        from deepspeed_tpu.ops.transformer import (
            DeepSpeedTransformerConfig,
            DeepSpeedTransformerLayer,
        )

        for pre_ln in (False, True):
            cfg = DeepSpeedTransformerConfig(
                hidden_size=32, intermediate_size=64, heads=2,
                attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
                pre_layer_norm=pre_ln, training=False)
            layer = DeepSpeedTransformerLayer(cfg)
            x = jnp.ones((2, 8, 32))
            mask = jnp.ones((2, 8), jnp.int32)
            params = layer.init(jax.random.PRNGKey(0), x, mask)
            out = layer.apply(params, x, mask)
            assert out.shape == x.shape

    def test_matches_bert_layer_post_ln(self):
        """Post-LN DeepSpeedTransformerLayer ≡ BertLayer numerics (the
        reference's kernel-vs-HF-BERT equivalence test shape)."""
        from deepspeed_tpu.models.bert import BertConfig, BertLayer
        from deepspeed_tpu.ops.transformer import (
            DeepSpeedTransformerConfig,
            DeepSpeedTransformerLayer,
        )

        cfg = DeepSpeedTransformerConfig(
            hidden_size=32, intermediate_size=64, heads=2,
            attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
            pre_layer_norm=False, training=False)
        layer = DeepSpeedTransformerLayer(cfg)
        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((2, 8, 32)).astype(np.float32))
        params = layer.init(jax.random.PRNGKey(0), x)
        out = layer.apply(params, x)

        bcfg = BertConfig(hidden_size=32, num_attention_heads=2,
                          intermediate_size=64, hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)
        ref_layer = BertLayer(bcfg)
        ref_out = ref_layer.apply(
            {"params": params["params"]["layer"]}, x, None, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                                   rtol=1e-5, atol=1e-6)
