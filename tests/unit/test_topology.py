"""Topology/mesh unit tests (analog of reference tests for
runtime/pipe/topology.py)."""

import pytest

from deepspeed_tpu.parallel import (
    MeshConfig,
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    ProcessTopology,
    get_data_parallel_world_size,
    get_model_parallel_world_size,
    initialize_mesh,
)


def test_topology_rank_coord_roundtrip():
    topo = ProcessTopology(["pipe", "data", "model"], [2, 2, 2])
    assert topo.world_size() == 8
    for rank in range(8):
        coord = topo.get_coord(rank)
        assert topo.get_rank(pipe=coord.pipe, data=coord.data, model=coord.model) == rank


def test_topology_axis_comm_lists():
    topo = ProcessTopology(["pipe", "data"], [2, 4])
    data_lists = topo.get_axis_comm_lists("data")
    assert data_lists == [[0, 1, 2, 3], [4, 5, 6, 7]]
    pipe_lists = topo.get_axis_comm_lists("pipe")
    assert pipe_lists == [[0, 4], [1, 5], [2, 6], [3, 7]]


def test_topology_filter_match():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    assert topo.filter_match(pipe=0) == [0, 1, 2, 3]
    assert topo.filter_match(pipe=1, model=1) == [5, 7]


def test_rank_repr():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=1)
    # data axis omitted by default, like reference checkpoint naming
    assert topo.get_rank_repr(0) == "pipe_00-model_00"
    assert topo.get_rank_repr(3) == "pipe_01-model_01"


def test_pipe_data_topology():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=4)
    assert topo.get_dim("pipe") == 2
    assert topo.get_dim("data") == 4


def test_mesh_config_resolve():
    cfg = MeshConfig(model=2).resolve(8)
    assert cfg.data == 4
    with pytest.raises(ValueError):
        MeshConfig(model=3).resolve(8)


def test_initialize_mesh_dp_world():
    initialize_mesh(model=2)
    assert get_data_parallel_world_size() == 4
    assert get_model_parallel_world_size() == 2


def test_initialize_mesh_default_all_data():
    mesh = initialize_mesh()
    assert get_data_parallel_world_size() == 8
    assert dict(zip(mesh.axis_names, mesh.devices.shape))["data"] == 8
