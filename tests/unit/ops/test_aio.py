"""AIO handle + sweep tests — analog of reference ``tests/unit/ops/aio/``
and the ``csrc/aio/py_test`` validation suite."""

import os

import numpy as np
import pytest

from deepspeed_tpu.ops.aio import AioHandle, aio_available, aligned_array
from deepspeed_tpu.ops.aio.sweep import sweep, sync_baseline, validate

pytestmark = pytest.mark.skipif(not aio_available(),
                                reason="aio lib unavailable")


def test_roundtrip_basic(tmp_path):
    h = AioHandle(num_threads=2)
    data = np.random.default_rng(0).integers(0, 255, 1 << 20, dtype=np.uint8)
    path = str(tmp_path / "x.bin")
    h.async_pwrite(data, path)
    h.wait()
    out = np.empty_like(data)
    h.async_pread(out, path)
    h.wait()
    np.testing.assert_array_equal(out, data)
    h.close()


def test_offsets_and_partial_reads(tmp_path):
    h = AioHandle(num_threads=2, block_size=64 * 1024)
    data = np.arange(1 << 18, dtype=np.uint8)
    path = str(tmp_path / "x.bin")
    h.async_pwrite(data, path)
    h.wait()
    # read a window at a non-zero, non-aligned offset
    out = np.empty(1000, np.uint8)
    h.async_pread(out, path, offset=12345)
    h.wait()
    np.testing.assert_array_equal(out, data[12345:13345])
    # write a window back at an offset
    h.async_pwrite(np.full(1000, 7, np.uint8), path, offset=500)
    h.wait()
    full = np.fromfile(path, np.uint8)
    assert (full[500:1500] == 7).all()
    assert full[499] == data[499]
    h.close()


def test_block_splitting_many_chunks(tmp_path):
    # tiny block size → many chunks across threads; content must be exact
    h = AioHandle(num_threads=4, block_size=4096, queue_depth=8)
    data = np.random.default_rng(1).integers(0, 255, (1 << 20) + 777,
                                             dtype=np.uint8)
    path = str(tmp_path / "x.bin")
    h.async_pwrite(data, path)
    h.wait()
    out = np.empty_like(data)
    h.async_pread(out, path)
    h.wait()
    np.testing.assert_array_equal(out, data)
    h.close()


def test_o_direct_roundtrip(tmp_path):
    h = AioHandle(num_threads=2, block_size=64 * 1024, o_direct=True)
    data = aligned_array(1 << 20)
    data[:] = np.random.default_rng(2).integers(0, 255, data.size,
                                                dtype=np.uint8)
    path = str(tmp_path / "x.bin")
    h.async_pwrite(data, path)
    h.wait()
    out = aligned_array(data.size)
    h.async_pread(out, path)
    h.wait()
    np.testing.assert_array_equal(out, data)
    # unaligned tail falls back to the buffered fd — still exact
    odd = np.empty(4096 + 123, np.uint8)
    h.async_pread(odd, path, offset=1)
    h.wait()
    np.testing.assert_array_equal(odd, np.asarray(data)[1:1 + odd.size])
    h.close()


def test_wait_reports_failures(tmp_path):
    h = AioHandle(num_threads=1)
    out = np.empty(128, np.uint8)
    h.async_pread(out, str(tmp_path / "does_not_exist.bin"))
    with pytest.raises(IOError):
        h.wait()
    h.close()


def test_aligned_array_alignment():
    for n in (1, 100, 4096, 123457):
        a = aligned_array(n)
        assert a.ctypes.data % 4096 == 0
        assert a.nbytes == n


def test_validate_grid(tmp_path):
    assert validate(dir=str(tmp_path), nbytes=1 << 20)


def test_sweep_structure_and_sanity(tmp_path):
    """The sweep produces measured bandwidths per config. The async>sync
    claim itself is recorded from a full-size run in BASELINE.md (buffered
    ~3x, O_DIRECT ~2x); a strict >1x assertion here would be a timing race
    on small files / loaded CI hosts, so only sanity is asserted."""
    out = sweep(file_mb=64, dir=str(tmp_path),
                block_sizes=(1 << 20, 8 << 20), threads=(2, 4))
    assert out["baseline_gbps"] > 0
    assert len(out["results"]) == 4
    assert out["best"]["read_gbps"] > 0
    assert out["results"] == sorted(out["results"],
                                    key=lambda r: -r["read_gbps"])
    # best multi-threaded chunked read should not be dramatically SLOWER
    # than sync (that would indicate a scheduling bug, not host noise)
    assert out["best"]["speedup_vs_sync"] > 0.5, out
