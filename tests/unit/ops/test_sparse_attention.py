"""Block-sparse attention tests (≅ reference tests/unit/ops/sparse_attention):
layout structure per config family + kernel numerics vs dense-masked
reference + differentiability."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig,
    SparseSelfAttention,
    VariableSparsityConfig,
    block_sparse_attention,
)

H, BLOCK, T = 4, 8, 64
NB = T // BLOCK


def _dense_masked_reference(q, k, v, layout, block, causal):
    """Token-level dense attention with the block layout expanded to a
    token mask — the ground truth the kernel must match."""
    B, T, H, D = q.shape
    tok_mask = np.kron(layout, np.ones((block, block)))  # (H, T, T)
    if causal:
        tok_mask = tok_mask * np.tril(np.ones((T, T)))
    s = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(D)
    s = jnp.where(jnp.asarray(tok_mask[None]) > 0, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.asarray(tok_mask[None]) > 0, p, 0.0)
    return jnp.einsum("bhts,bshd->bthd", p, v)


CONFIGS = {
    "dense": DenseSparsityConfig(num_heads=H, block=BLOCK),
    "fixed": FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                                 num_global_blocks=1, attention="unidirectional"),
    "variable": VariableSparsityConfig(num_heads=H, block=BLOCK,
                                       num_random_blocks=2,
                                       local_window_blocks=[2, 4],
                                       global_block_indices=[0]),
    "bigbird": BigBirdSparsityConfig(num_heads=H, block=BLOCK,
                                     num_random_blocks=1,
                                     num_sliding_window_blocks=3,
                                     num_global_blocks=1),
    "bslongformer": BSLongformerSparsityConfig(num_heads=H, block=BLOCK,
                                               num_sliding_window_blocks=3,
                                               global_block_indices=[0]),
    "sliding": LocalSlidingWindowSparsityConfig(num_heads=H, block=BLOCK,
                                                num_sliding_window_blocks=3),
}


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_layout_structure(name):
    cfg = CONFIGS[name]
    layout = cfg.make_layout(T)
    assert layout.shape == (H, NB, NB)
    assert ((layout == 0) | (layout == 1)).all()
    # every query block must attend to at least one block (diag is always in)
    if getattr(cfg, "attention", "bidirectional") == "unidirectional":
        assert (np.triu(layout, 1) == 0).all(), "causal layout leaks future"
    assert (layout.sum(-1) >= 1).all()


def test_sliding_window_exact_shape():
    layout = CONFIGS["sliding"].make_layout(T)
    # row i attends to blocks [i-1, i] (w=1, unidirectional)
    for i in range(NB):
        expect = set(range(max(0, i - 1), i + 1))
        assert set(np.nonzero(layout[0, i])[0]) == expect


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_kernel_matches_dense_masked(name):
    cfg = CONFIGS[name]
    layout = cfg.make_layout(T)
    causal = getattr(cfg, "attention", "bidirectional") == "unidirectional"
    rng = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(r, (2, T, H, 16), jnp.float32) for r in rng)
    got = block_sparse_attention(q, k, v, layout, BLOCK, causal=causal)
    want = _dense_masked_reference(q, k, v, layout, BLOCK, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_sparse_attention_differentiable():
    cfg = CONFIGS["bigbird"]
    layout = cfg.make_layout(T)
    rng = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(r, (1, T, H, 8), jnp.float32) for r in rng)

    g = jax.grad(lambda q, k, v: jnp.sum(
        block_sparse_attention(q, k, v, layout, BLOCK) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: jnp.sum(
        _dense_masked_reference(q, k, v, layout, BLOCK, False) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_sparse_self_attention_module():
    attn = SparseSelfAttention(CONFIGS["fixed"])
    rng = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(r, (2, T, H, 16), jnp.float32) for r in rng)
    out = attn(q, k, v)
    assert out.shape == q.shape
    assert np.isfinite(np.asarray(out)).all()
    # layout cache hit
    assert T in attn._layouts
