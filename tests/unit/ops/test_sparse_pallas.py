"""Pallas block-sparse flash attention vs the gather formulation.

The gather path (``sparse_self_attention.block_sparse_attention``) is the
numerics reference (itself tested against dense attention in
test_sparse_attention.py); these tests pin the fused kernel to it fwd+bwd
across the sparsity-config vocabulary, plus the routing rules.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention.pallas_kernel import (
    MIN_KERNEL_BLOCK,
    block_sparse_flash_attention,
    layout_to_schedule,
    supports_pallas,
)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    SparseSelfAttention,
    block_sparse_attention,
)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig,
    FixedSparsityConfig,
)

BLOCK = 128


def _qkv(rng, B=1, T=512, H=2, D=64):
    mk = lambda: jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    return mk(), mk(), mk()


def _local_global_layout(H, nq):
    layout = np.zeros((H, nq, nq), np.int32)
    for h in range(H):
        for i in range(nq):
            layout[h, i, i] = 1
            if i > 0:
                layout[h, i, i - 1] = 1
            layout[h, i, 0] = 1
    return layout


def test_layout_to_schedule_padding_repeats_last():
    layout = np.zeros((1, 3, 4), np.int32)
    layout[0, 0, [1, 3]] = 1
    layout[0, 1, 2] = 1
    # row 2 empty
    idx, cnt = layout_to_schedule(layout)
    assert idx.shape == (1, 3, 2)
    assert cnt.tolist() == [[2, 1, 0]]
    assert idx[0, 0].tolist() == [1, 3]
    assert idx[0, 1].tolist() == [2, 2]   # padded with last live index
    assert idx[0, 2].tolist() == [0, 0]   # empty row points at block 0


@pytest.mark.parametrize("causal", [False, True])
def test_fwd_bwd_matches_gather(causal):
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng)
    layout = _local_global_layout(2, q.shape[1] // BLOCK)

    ref = block_sparse_attention(q, k, v, layout, BLOCK, causal=causal)
    out = block_sparse_flash_attention(q, k, v, layout, BLOCK, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    def loss(fn):
        return lambda *a: jnp.sum(fn(*a, layout, BLOCK, causal=causal) ** 2)

    g_ref = jax.grad(loss(block_sparse_attention), argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss(block_sparse_flash_attention),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=5e-4)


def test_empty_rows_produce_zero_output():
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, T=256)
    layout = np.zeros((2, 2, 2), np.int32)
    layout[:, 0, 0] = 1  # q-block 1 attends nothing
    out = block_sparse_flash_attention(q, k, v, layout, BLOCK, causal=False)
    np.testing.assert_allclose(np.asarray(out[:, BLOCK:]), 0.0, atol=1e-6)


def test_different_layout_per_head():
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, T=512)
    nq = 4
    layout = _local_global_layout(2, nq)
    layout[1] = np.eye(nq, dtype=np.int32)  # head 1: diagonal only
    ref = block_sparse_attention(q, k, v, layout, BLOCK, causal=False)
    out = block_sparse_flash_attention(q, k, v, layout, BLOCK, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


@pytest.mark.parametrize("cfg_cls,kwargs", [
    (FixedSparsityConfig, dict(num_local_blocks=2, num_global_blocks=1,
                               attention="unidirectional")),
    (BigBirdSparsityConfig, dict(num_random_blocks=1, num_sliding_window_blocks=2,
                                 num_global_blocks=1)),
])
def test_sparsity_config_vocabulary(cfg_cls, kwargs):
    rng = np.random.default_rng(3)
    q, k, v = _qkv(rng, T=512)
    cfg = cfg_cls(num_heads=2, block=BLOCK, **kwargs)
    layout = cfg.make_layout(q.shape[1])
    causal = getattr(cfg, "attention", "bidirectional") == "unidirectional"
    ref = block_sparse_attention(q, k, v, layout, BLOCK, causal=causal)
    out = block_sparse_flash_attention(q, k, v, layout, BLOCK, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_module_routes_to_pallas_for_coarse_blocks():
    rng = np.random.default_rng(4)
    q, k, v = _qkv(rng, T=512)
    cfg = FixedSparsityConfig(num_heads=2, block=BLOCK, num_local_blocks=2,
                              num_global_blocks=1)
    auto = SparseSelfAttention(cfg)(q, k, v)
    gather = SparseSelfAttention(cfg, kernel="gather")(q, k, v)
    np.testing.assert_allclose(np.asarray(auto), np.asarray(gather), atol=1e-4)


def test_module_falls_back_for_fine_blocks():
    rng = np.random.default_rng(5)
    q, k, v = _qkv(rng, T=128)
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2,
                              num_global_blocks=1)
    out = SparseSelfAttention(cfg)(q, k, v)  # auto → gather, no error
    assert out.shape == q.shape
    assert not supports_pallas(16, 128)
    with pytest.raises(ValueError):
        block_sparse_flash_attention(q, k, v, cfg.make_layout(128), 16)


def test_supports_pallas_rules():
    assert supports_pallas(MIN_KERNEL_BLOCK, 512)
    assert not supports_pallas(64, 512)       # sub-MXU granule
    assert not supports_pallas(MIN_KERNEL_BLOCK, 500)  # non-divisible seq
