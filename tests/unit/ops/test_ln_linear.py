"""Fused LayerNorm->Linear kernel numerics (ops/transformer/ln_linear.py).

The kernel-vs-plain-composition parity tests follow the reference's
kernel-vs-PyTorch pattern (tests/unit/ops/transformer) — here the oracle
is the unfused jnp composition, and the model-level test asserts the
fused block is a drop-in (identical param tree, matching loss/grads).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.ln_linear import (
    ln_linear,
    supports_fused,
)


def _reference(x, gamma, beta, w, bias, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    xh = xc * jax.lax.rsqrt(var + eps)
    n = (xh * gamma.astype(jnp.float32) +
         beta.astype(jnp.float32)).astype(x.dtype)
    return n @ w.astype(x.dtype) + bias.astype(x.dtype)


def _make(m, c, n, dtype, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((m, c)), dtype)
    gamma = jnp.asarray(1.0 + 0.1 * rng.standard_normal(c), jnp.float32)
    beta = jnp.asarray(0.1 * rng.standard_normal(c), jnp.float32)
    w = jnp.asarray(rng.standard_normal((c, n)) / np.sqrt(c), dtype)
    bias = jnp.asarray(0.1 * rng.standard_normal(n), jnp.float32)
    return x, gamma, beta, w, bias


@pytest.mark.parametrize("m,c,n", [(64, 128, 256), (128, 256, 128)])
def test_forward_matches_reference(m, c, n):
    args = _make(m, c, n, jnp.bfloat16)
    assert supports_fused(m, c, n)
    got = ln_linear(*args)
    want = _reference(*args)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_gradients_match_reference():
    m, c, n = 64, 128, 128
    x, gamma, beta, w, bias = _make(m, c, n, jnp.bfloat16)

    def loss_fused(args):
        return ln_linear(*args).astype(jnp.float32).sum()

    def loss_ref(args):
        return _reference(*args).astype(jnp.float32).sum()

    gf = jax.grad(loss_fused)((x, gamma, beta, w, bias))
    gr = jax.grad(loss_ref)((x, gamma, beta, w, bias))
    for a, b, name in zip(gf, gr, ("dx", "dgamma", "dbeta", "dw", "dbias")):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=0.15, rtol=5e-2, err_msg=name)


def test_ragged_shapes_fall_back():
    # M=9 has no MXU-aligned tile; the public API must still be exact
    m, c, n = 9, 128, 128
    args = _make(m, c, n, jnp.float32)
    assert not supports_fused(m, c, n)
    got = ln_linear(*args)
    want = _reference(*args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_leading_dims_flattened():
    b, t, c, n = 2, 32, 128, 128
    x3 = jnp.asarray(np.random.default_rng(1).standard_normal((b, t, c)),
                     jnp.bfloat16)
    _, gamma, beta, w, bias = _make(b * t, c, n, jnp.bfloat16, seed=1)
    got = ln_linear(x3, gamma, beta, w, bias)
    want = _reference(x3, gamma, beta, w, bias)
    assert got.shape == (b, t, n)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_model_level_fused_block_is_drop_in():
    """Fused and unfused GPT-2 blocks: identical param trees, matching
    loss and grads (the A/B the flagship bench toggles)."""
    import jax.tree_util as jtu

    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 64, (2, 64)).astype(np.int32)}

    def build(fused):
        cfg = GPT2Config(vocab_size=64, n_positions=64, n_embd=128,
                         n_layer=2, n_head=2, dtype=jnp.bfloat16,
                         use_flash_attention=False, fused_ln_linear=fused,
                         remat=True, remat_policy="dots")
        return GPT2LMHeadModel(cfg)

    m_f, m_u = build(True), build(False)
    p_f = m_f.init({"params": jax.random.PRNGKey(0)}, batch)
    p_u = m_u.init({"params": jax.random.PRNGKey(0)}, batch)
    kf = [jtu.keystr(k) for k, _ in jtu.tree_flatten_with_path(p_f)[0]]
    ku = [jtu.keystr(k) for k, _ in jtu.tree_flatten_with_path(p_u)[0]]
    assert kf == ku

    lf, gf = jax.value_and_grad(lambda p: m_f.apply(p, batch))(p_u)
    lu, gu = jax.value_and_grad(lambda p: m_u.apply(p, batch))(p_u)
    assert abs(float(lf) - float(lu)) < 2e-2
    errs = [float(jnp.max(jnp.abs(a.astype(jnp.float32) -
                                  b.astype(jnp.float32))))
            for a, b in zip(jtu.tree_leaves(gf), jtu.tree_leaves(gu))]
    assert max(errs) < 6e-2, max(errs)
