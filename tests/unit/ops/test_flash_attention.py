"""Flash-attention kernel numerics vs plain-jnp reference (analog of the
reference's kernel-vs-PyTorch tests in tests/unit/ops/transformer/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention.flash_attention import (
    flash_attention,
    mha_reference,
)


def _rand_qkv(b=2, t=256, h=4, d=64, dtype=jnp.float32, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(k1, (b, t, h, d), dtype)
    k = jax.random.normal(k2, (b, t, h, d), dtype)
    v = jax.random.normal(k3, (b, t, h, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(causal):
    q, k, v = _rand_qkv()
    out = flash_attention(q, k, v, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_forward_multiple_q_blocks():
    q, k, v = _rand_qkv(t=512)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=64)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_backward_matches_reference(causal):
    q, k, v = _rand_qkv(b=1, t=128, h=2, d=32)

    def f_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal) ** 2).sum()

    def f_ref(q, k, v):
        return (mha_reference(q, k, v, causal=causal) ** 2).sum()

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3)


def test_bf16_forward():
    q, k, v = _rand_qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref, dtype=np.float32), rtol=5e-2, atol=5e-2)


def test_gpt2_with_flash_attention_trains():
    import deepspeed_tpu as ds
    from tests.unit.simple_model import base_config, token_batch, tiny_gpt2

    model = tiny_gpt2(n_embd=64, n_head=2, n_positions=128, use_flash_attention=True)
    engine, _, _, _ = ds.initialize(model=model, config=base_config(micro=1))
    batch = token_batch(8, seq=128)
    l0 = float(engine.train_batch(batch=batch))
    for _ in range(3):
        loss = engine.train_batch(batch=batch)
    assert float(loss) < l0
