"""Fused decode-attention kernel tests (reference softmax_context analog,
pt_binding.cpp:1910-1975). Pallas runs in interpreter mode on CPU."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention.decode_attention import (
    decode_attention,
    pick_block_s,
)


def _reference(q, k, v, lengths, slopes=None):
    B, H, D = q.shape
    _, KV, S, _ = k.shape
    rep = H // KV
    k = jnp.repeat(k, rep, axis=1)
    v = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / math.sqrt(D)
    pos = jnp.arange(S)[None, None, :]
    if slopes is not None:
        s = s + slopes[None, :, None] * (pos - (lengths[:, None, None] - 1))
    s = jnp.where(pos < lengths[:, None, None], s, -1e30)
    return jnp.einsum("bhs,bhsd->bhd", jax.nn.softmax(s, axis=-1),
                      v.astype(jnp.float32))


@pytest.mark.parametrize("B,H,KV,D,S,block", [
    (2, 4, 4, 64, 128, 64),     # MHA
    (2, 8, 2, 64, 256, 128),    # GQA 4x
    (1, 4, 1, 128, 256, 256),   # MQA
])
def test_matches_reference(B, H, KV, D, S, block):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    lengths = jnp.asarray(rng.integers(1, S + 1, B), jnp.int32)
    out = decode_attention(q, k, v, lengths, block_s=block)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_reference(q, k, v, lengths)),
                               atol=1e-4, rtol=1e-4)


def test_alibi_bias():
    rng = np.random.default_rng(1)
    B, H, D, S = 2, 4, 64, 128
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    lengths = jnp.asarray([100, 37], jnp.int32)
    slopes = jnp.asarray(rng.standard_normal(H) * 0.1, jnp.float32)
    out = decode_attention(q, k, v, lengths, alibi_slopes=slopes, block_s=64)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_reference(q, k, v, lengths, slopes)),
        atol=1e-4, rtol=1e-4)


def test_scalar_length_broadcasts():
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((3, 2, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((3, 2, 64, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((3, 2, 64, 64)), jnp.float32)
    out = decode_attention(q, k, v, jnp.asarray(17, jnp.int32), block_s=64)
    expect = _reference(q, k, v, jnp.full(3, 17, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               atol=1e-4, rtol=1e-4)


def test_pick_block_s():
    assert pick_block_s(2048) == 1024  # tuned default (attn_bench r3 sweep)
    assert pick_block_s(512) == 512
    assert pick_block_s(192) == 64
    assert pick_block_s(100) == 4   # 100 = 4 * 25
    # length-aware preference: >= 8k caches take the 4096 block the
    # round-5 sweep measured fastest (kv_int8_results.json block rows)
    assert pick_block_s(8192) == 4096
    assert pick_block_s(16384) == 4096
    assert pick_block_s(4096) == 1024
    assert pick_block_s(97) == 1


def test_model_decode_kernel_matches_jnp_path():
    """CachedAttention with decode_kernel on vs off: same generation."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )

    prompts = np.arange(6, dtype=np.int32)[None] % 32

    def gen(mode):
        cfg = TransformerConfig(vocab_size=32, max_seq_len=64, n_embd=64,
                                n_layer=2, n_head=2, dtype=jnp.float32,
                                decode_kernel=mode)
        eng = ds.init_inference(TransformerLM(cfg), config={"dtype": "fp32"})
        return eng.generate(prompts, max_new_tokens=8)

    out_off = gen("off")
    out_on = gen("on")
    np.testing.assert_array_equal(out_on, out_off)


def test_bf16_matches_reference():
    """bf16 inputs exercise the actual production path (round 5: MXU
    operands stay bf16 — the fp32 tests above are byte-identical to the
    pre-change kernel, so this is the only coverage of the changed dots
    and of the p -> bf16 downcast before the p.V dot)."""
    B, H, KV, D, S = 2, 4, 2, 64, 256
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.bfloat16)
    lengths = jnp.asarray([S, S // 3], jnp.int32)
    out = decode_attention(q, k, v, lengths, block_s=64)
    assert out.dtype == jnp.bfloat16
    ref = _reference(q, k, v, lengths)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_mixed_dtype_query_is_harmonized():
    """fp32 queries against a bf16 cache must not raise (the wrapper
    casts q to the cache dtype and restores the caller's dtype out)."""
    B, H, KV, D, S = 1, 2, 2, 64, 128
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.bfloat16)
    out = decode_attention(q, k, v, jnp.asarray([S], jnp.int32), block_s=64)
    assert out.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(out)))


@pytest.mark.parametrize("B,H,KV,D,S,block", [
    (2, 4, 4, 64, 128, 64),     # MHA
    (2, 8, 2, 64, 256, 128),    # GQA 4x
])
def test_int8_kv_cache_matches_dequantized_reference(B, H, KV, D, S, block):
    """int8 cache + per-row scales: the kernel must compute EXACTLY the
    attention over the dequantized cache (int8 * scale), to fp32/bf16
    tolerance — quantization error lives in the cache contents only."""
    from deepspeed_tpu.ops.attention.decode_attention import quantize_kv_rows

    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    lengths = jnp.asarray(rng.integers(1, S + 1, B), jnp.int32)

    k8, ks = quantize_kv_rows(k)
    v8, vs = quantize_kv_rows(v)
    out = decode_attention(q, k8, v8, lengths, k_scale=ks, v_scale=vs,
                           block_s=block)
    k_deq = k8.astype(jnp.float32) * ks[..., None]
    v_deq = v8.astype(jnp.float32) * vs[..., None]
    ref = _reference(q, k_deq, v_deq, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
    # and the quantized result tracks the full-precision one closely
    full = _reference(q, k, v, lengths)
    err = np.max(np.abs(np.asarray(out) - np.asarray(full)))
    assert err < 0.05, f"int8 KV quantization error too large: {err}"


def test_int8_kv_cache_bf16_query():
    from deepspeed_tpu.ops.attention.decode_attention import quantize_kv_rows

    rng = np.random.default_rng(2)
    B, H, KV, D, S = 1, 4, 2, 64, 128
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    lengths = jnp.asarray([97], jnp.int32)
    k8, ks = quantize_kv_rows(k)
    v8, vs = quantize_kv_rows(v)
    out = decode_attention(q, k8, v8, lengths, k_scale=ks, v_scale=vs,
                           block_s=64)
    assert out.dtype == jnp.bfloat16
    k_deq = k8.astype(jnp.float32) * ks[..., None]
    v_deq = v8.astype(jnp.float32) * vs[..., None]
    ref = _reference(q.astype(jnp.float32), k_deq, v_deq, lengths)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=0.03, rtol=0.03)


@pytest.mark.parametrize("kernel_mode", ["on", "off"])
def test_model_int8_kv_cache_generates_same_tokens(kernel_mode):
    """kv_cache_quant=True end-to-end: the cache leaves are int8 with
    per-row scales, and greedy generation matches the full-precision
    cache (tiny model: quantization noise below the argmax margin) on
    both the fused-kernel and einsum decode paths."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )

    prompts = np.arange(6, dtype=np.int32)[None] % 32

    def gen(quant):
        cfg = TransformerConfig(vocab_size=32, max_seq_len=64, n_embd=64,
                                n_layer=2, n_head=2, dtype=jnp.float32,
                                decode_kernel=kernel_mode,
                                kv_cache_quant=quant)
        eng = ds.init_inference(TransformerLM(cfg), config={"dtype": "fp32"})
        toks = eng.generate(prompts, max_new_tokens=8)
        return toks, eng

    toks_q, eng_q = gen(True)
    toks_f, _ = gen(False)
    np.testing.assert_array_equal(toks_q, toks_f)

    # the cache really is int8 + scales (half the bytes of bf16)
    _, cache = eng_q._jit_prefill(eng_q.params, prompts)
    leaves = jax.tree_util.tree_leaves_with_path(cache)
    kv = [lf for p, lf in leaves
          if any(getattr(x, "key", None) in ("k", "v") for x in p)]
    scales = [lf for p, lf in leaves
              if any(getattr(x, "key", None) in ("k_scale", "v_scale")
                     for x in p)]
    assert kv and all(lf.dtype == jnp.int8 for lf in kv)
    assert scales and all(lf.dtype == jnp.float32 for lf in scales)
