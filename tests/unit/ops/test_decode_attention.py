"""Fused decode-attention kernel tests (reference softmax_context analog,
pt_binding.cpp:1910-1975). Pallas runs in interpreter mode on CPU."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention.decode_attention import (
    decode_attention,
    pick_block_s,
)


def _ds(cache):
    """Tests build caches (B, KV, S, D) for readability; the kernel takes
    the model's positions-minor (B, KV, D, S) layout."""
    return cache.transpose(0, 1, 3, 2)


def _reference(q, k, v, lengths, slopes=None):
    B, H, D = q.shape
    _, KV, S, _ = k.shape
    rep = H // KV
    k = jnp.repeat(k, rep, axis=1)
    v = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / math.sqrt(D)
    pos = jnp.arange(S)[None, None, :]
    if slopes is not None:
        s = s + slopes[None, :, None] * (pos - (lengths[:, None, None] - 1))
    s = jnp.where(pos < lengths[:, None, None], s, -1e30)
    return jnp.einsum("bhs,bhsd->bhd", jax.nn.softmax(s, axis=-1),
                      v.astype(jnp.float32))


@pytest.mark.parametrize("B,H,KV,D,S,block", [
    (2, 4, 4, 64, 128, 64),     # MHA
    (2, 8, 2, 64, 256, 128),    # GQA 4x
    (1, 4, 1, 128, 256, 256),   # MQA
])
def test_matches_reference(B, H, KV, D, S, block):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    lengths = jnp.asarray(rng.integers(1, S + 1, B), jnp.int32)
    out = decode_attention(q, _ds(k), _ds(v), lengths, block_s=block)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_reference(q, k, v, lengths)),
                               atol=1e-4, rtol=1e-4)


def test_alibi_bias():
    rng = np.random.default_rng(1)
    B, H, D, S = 2, 4, 64, 128
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    lengths = jnp.asarray([100, 37], jnp.int32)
    slopes = jnp.asarray(rng.standard_normal(H) * 0.1, jnp.float32)
    out = decode_attention(q, _ds(k), _ds(v), lengths, alibi_slopes=slopes,
                           block_s=64)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_reference(q, k, v, lengths, slopes)),
        atol=1e-4, rtol=1e-4)


def test_scalar_length_broadcasts():
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((3, 2, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((3, 2, 64, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((3, 2, 64, 64)), jnp.float32)
    out = decode_attention(q, _ds(k), _ds(v), jnp.asarray(17, jnp.int32),
                           block_s=64)
    expect = _reference(q, k, v, jnp.full(3, 17, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               atol=1e-4, rtol=1e-4)


def test_pick_block_s():
    assert pick_block_s(2048) == 1024  # tuned default (attn_bench r3 sweep)
    assert pick_block_s(512) == 512
    assert pick_block_s(192) == 64
    assert pick_block_s(100) == 4   # 100 = 4 * 25
    # length-aware preference: >= 8k caches take the 4096 block the
    # round-5 sweep measured fastest (kv_int8_results.json block rows)
    assert pick_block_s(8192) == 4096
    assert pick_block_s(16384) == 4096
    assert pick_block_s(4096) == 1024
    assert pick_block_s(97) == 1


def test_model_decode_kernel_matches_jnp_path():
    """CachedAttention with decode_kernel on vs off: same generation."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )

    prompts = np.arange(6, dtype=np.int32)[None] % 32

    def gen(mode):
        cfg = TransformerConfig(vocab_size=32, max_seq_len=64, n_embd=64,
                                n_layer=2, n_head=2, dtype=jnp.float32,
                                decode_kernel=mode)
        eng = ds.init_inference(TransformerLM(cfg), config={"dtype": "fp32"})
        return eng.generate(prompts, max_new_tokens=8)

    out_off = gen("off")
    out_on = gen("on")
    np.testing.assert_array_equal(out_on, out_off)


def test_bf16_matches_reference():
    """bf16 inputs exercise the actual production path (round 5: MXU
    operands stay bf16 — the fp32 tests above are byte-identical to the
    pre-change kernel, so this is the only coverage of the changed dots
    and of the p -> bf16 downcast before the p.V dot)."""
    B, H, KV, D, S = 2, 4, 2, 64, 256
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.bfloat16)
    lengths = jnp.asarray([S, S // 3], jnp.int32)
    out = decode_attention(q, _ds(k), _ds(v), lengths, block_s=64)
    assert out.dtype == jnp.bfloat16
    ref = _reference(q, k, v, lengths)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_mixed_dtype_query_is_harmonized():
    """fp32 queries against a bf16 cache must not raise (the wrapper
    casts q to the cache dtype and restores the caller's dtype out)."""
    B, H, KV, D, S = 1, 2, 2, 64, 128
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.bfloat16)
    out = decode_attention(q, _ds(k), _ds(v), jnp.asarray([S], jnp.int32),
                           block_s=64)
    assert out.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(out)))


@pytest.mark.parametrize("B,H,KV,D,S,block", [
    (2, 4, 4, 64, 128, 64),     # MHA
    (2, 8, 2, 64, 256, 128),    # GQA 4x
])
def test_int8_kv_cache_matches_dequantized_reference(B, H, KV, D, S, block):
    """int8 cache + per-row scales: the kernel must compute EXACTLY the
    attention over the dequantized cache (int8 * scale), to fp32/bf16
    tolerance — quantization error lives in the cache contents only."""
    from deepspeed_tpu.ops.attention.decode_attention import quantize_kv_rows

    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    lengths = jnp.asarray(rng.integers(1, S + 1, B), jnp.int32)

    k8, ks = quantize_kv_rows(k)
    v8, vs = quantize_kv_rows(v)
    out = decode_attention(q, _ds(k8), _ds(v8), lengths, k_scale=ks,
                           v_scale=vs, block_s=block)
    k_deq = k8.astype(jnp.float32) * ks[..., None]
    v_deq = v8.astype(jnp.float32) * vs[..., None]
    ref = _reference(q, k_deq, v_deq, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
    # and the quantized result tracks the full-precision one closely
    full = _reference(q, k, v, lengths)
    err = np.max(np.abs(np.asarray(out) - np.asarray(full)))
    assert err < 0.05, f"int8 KV quantization error too large: {err}"


def test_int8_kv_cache_bf16_query():
    from deepspeed_tpu.ops.attention.decode_attention import quantize_kv_rows

    rng = np.random.default_rng(2)
    B, H, KV, D, S = 1, 4, 2, 64, 128
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    lengths = jnp.asarray([97], jnp.int32)
    k8, ks = quantize_kv_rows(k)
    v8, vs = quantize_kv_rows(v)
    out = decode_attention(q, _ds(k8), _ds(v8), lengths, k_scale=ks,
                           v_scale=vs, block_s=64)
    assert out.dtype == jnp.bfloat16
    k_deq = k8.astype(jnp.float32) * ks[..., None]
    v_deq = v8.astype(jnp.float32) * vs[..., None]
    ref = _reference(q.astype(jnp.float32), k_deq, v_deq, lengths)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=0.03, rtol=0.03)


@pytest.mark.parametrize("kernel_mode", ["on", "off"])
@pytest.mark.parametrize("packed", [True, False])
def test_model_int8_kv_cache_generates_same_tokens(kernel_mode, packed):
    """kv_cache_quant=True end-to-end: the cache leaves are int8 (or the
    int32 packed container — the default) with per-row scales, and greedy
    generation matches the full-precision cache (tiny model: quantization
    noise below the argmax margin) on both the fused-kernel and einsum
    decode paths."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )

    prompts = np.arange(6, dtype=np.int32)[None] % 32

    def gen(quant):
        cfg = TransformerConfig(vocab_size=32, max_seq_len=64, n_embd=64,
                                n_layer=2, n_head=2, dtype=jnp.float32,
                                decode_kernel=kernel_mode,
                                kv_cache_quant=quant,
                                kv_cache_packed=packed)
        eng = ds.init_inference(TransformerLM(cfg), config={"dtype": "fp32"})
        toks = eng.generate(prompts, max_new_tokens=8)
        return toks, eng

    toks_q, eng_q = gen(True)
    toks_f, _ = gen(False)
    np.testing.assert_array_equal(toks_q, toks_f)

    # the cache really is int8 + scales (half the bytes of bf16); packed
    # mode stores the same bytes 4-per-int32-word with head_dim/4 lanes
    _, cache = eng_q._jit_prefill(eng_q.params, prompts)
    leaves = jax.tree_util.tree_leaves_with_path(cache)
    kv = [lf for p, lf in leaves
          if any(getattr(x, "key", None) in ("k", "v") for x in p)]
    scales = [lf for p, lf in leaves
              if any(getattr(x, "key", None) in ("k_scale", "v_scale")
                     for x in p)]
    # cache layout is positions-minor (B, KV, D, S); packed mode holds 4
    # head-dim rows per int32 word
    want_dtype = jnp.int32 if packed else jnp.int8
    want_d = (64 // 2) // 4 if packed else 64 // 2  # head_dim=32
    assert kv and all(lf.dtype == want_dtype and lf.shape[-2] == want_d
                      and lf.shape[-1] == 64 for lf in kv)
    assert scales and all(lf.dtype == jnp.float32 for lf in scales)


def test_pack_int8_sublanes_round_trip():
    """pack/unpack are exact inverses; byte j of word i is row 4i+j (the
    TPU sublane byte order, so the kernel's bitcast is a free unpack)."""
    from deepspeed_tpu.ops.attention.decode_attention import (
        pack_int8_sublanes,
        unpack_int8_sublanes,
    )

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.integers(-127, 128, (2, 3, 8, 64)), jnp.int8)
    w = pack_int8_sublanes(x)
    assert w.dtype == jnp.int32 and w.shape == (2, 3, 2, 64)
    np.testing.assert_array_equal(np.asarray(unpack_int8_sublanes(w)),
                                  np.asarray(x))
    # byte 0 of word i is row 4i, sign bits included
    np.testing.assert_array_equal(
        np.asarray(w & 0xFF, np.uint8).astype(np.int8),
        np.asarray(x[..., ::4, :]))


@pytest.mark.parametrize("B,H,KV,D,S,block", [
    (2, 4, 4, 64, 128, 64),     # MHA
    (2, 8, 2, 64, 256, 128),    # GQA 4x
])
def test_packed_int8_kv_cache_matches_unpacked(B, H, KV, D, S, block):
    """The int32-packed cache path computes bit-identically to the plain
    int8 cache path (same quantized values, same kernel math)."""
    from deepspeed_tpu.ops.attention.decode_attention import (
        pack_int8_sublanes,
        quantize_kv_rows,
    )

    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    lengths = jnp.asarray(rng.integers(1, S + 1, B), jnp.int32)
    k8, ks = quantize_kv_rows(k)
    v8, vs = quantize_kv_rows(v)
    out_s8 = decode_attention(q, _ds(k8), _ds(v8), lengths, k_scale=ks,
                              v_scale=vs, block_s=block)
    out_i32 = decode_attention(q, pack_int8_sublanes(_ds(k8)),
                               pack_int8_sublanes(_ds(v8)),
                               lengths, k_scale=ks, v_scale=vs,
                               block_s=block)
    np.testing.assert_array_equal(np.asarray(out_i32), np.asarray(out_s8))


def test_block_hint_changes_block_not_tokens():
    """An explicit block hint must only change the kernel's block
    granule, never the outputs (the engine keeps the allocation-based
    default — the budget-derived hint measured net-negative)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )
    from deepspeed_tpu.ops.attention.decode_attention import (
        pick_block_s,
        preferred_block_for,
    )

    # the hint table: short budgets take the 1024 block, long the 4096
    assert preferred_block_for(1536) == 1024
    assert preferred_block_for(9000) == 4096
    assert pick_block_s(16384, preferred=1024) == 1024

    prompts = np.arange(6, dtype=np.int32)[None] % 32
    cfg = TransformerConfig(vocab_size=32, max_seq_len=256, n_embd=64,
                            n_layer=2, n_head=2, dtype=jnp.float32,
                            decode_kernel="on", kv_cache_quant=True)
    m = TransformerLM(cfg)
    eng = ds.init_inference(m, config={"dtype": "fp32"})
    toks_auto = eng.generate(prompts, max_new_tokens=8)

    # drive decode directly with an explicit tiny block hint: same logits
    params = eng._params_host
    _, vars_ = m.apply({"params": params}, prompts, method=m.prefill,
                       mutable=["cache"])
    step = jnp.asarray([[7]], jnp.int32)
    pos = jnp.asarray(prompts.shape[1], jnp.int32)
    l_default, _ = m.apply({"params": params, "cache": vars_["cache"]},
                           step, pos, method=m.decode, mutable=["cache"])
    l_hint, _ = m.apply({"params": params, "cache": vars_["cache"]},
                        step, pos, method=m.decode, mutable=["cache"],
                        block_hint=64)
    np.testing.assert_allclose(np.asarray(l_hint), np.asarray(l_default),
                               rtol=2e-5, atol=2e-5)
    assert toks_auto.shape == (1, prompts.shape[1] + 8)


def test_prefill_last_matches_full_prefill():
    """The generation-only prefill (last-position logits, the engine's
    generate() path) must produce bitwise the same cache as the full
    prefill and logits equal to its last row — sampling sees no
    difference, only the (B, T, V) prompt-logits allocation disappears."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )

    prompts = jnp.asarray(np.arange(7, dtype=np.int32)[None] % 32)
    cfg = TransformerConfig(vocab_size=32, max_seq_len=64, n_embd=64,
                            n_layer=2, n_head=2, dtype=jnp.float32,
                            kv_cache_quant=True)
    eng = ds.init_inference(TransformerLM(cfg), config={"dtype": "fp32"})
    eng.generate(np.asarray(prompts), max_new_tokens=2)  # init params
    m, p = TransformerLM(cfg), eng._params_host
    full, v1 = m.apply({"params": p}, prompts, method=m.prefill,
                       mutable=["cache"])
    last, v2 = m.apply({"params": p}, prompts, method=m.prefill_last,
                       mutable=["cache"])
    assert last.shape == (1, 1, 32)
    np.testing.assert_allclose(np.asarray(last[:, 0]),
                               np.asarray(full[:, -1]), rtol=1e-6)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(v1["cache"]),
            jax.tree_util.tree_leaves_with_path(v2["cache"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(pa))


def test_packed_chunked_decode_matches_unpacked():
    """Multi-token decode (T > 1, the windowed einsum fallback) over a
    packed cache: prefill at an unaligned length, then a 3-token chunk —
    logits must match the plain-int8 cache bit for bit (same quantized
    rows, the fallback unpacks the container)."""
    import deepspeed_tpu  # noqa: F401  (path setup)
    from deepspeed_tpu.models.transformer_lm import (
        TransformerConfig,
        TransformerLM,
    )

    prompts = jnp.asarray(np.arange(7, dtype=np.int32)[None] % 32)
    chunk = jnp.asarray([[3, 1, 4]], jnp.int32)

    def run(packed):
        cfg = TransformerConfig(vocab_size=32, max_seq_len=64, n_embd=64,
                                n_layer=2, n_head=2, dtype=jnp.float32,
                                decode_kernel="off", kv_cache_quant=True,
                                kv_cache_packed=packed)
        m = TransformerLM(cfg)
        params = m.init({"params": jax.random.PRNGKey(0)}, prompts,
                        method=m.prefill)["params"]
        _, vars_ = m.apply({"params": params}, prompts, method=m.prefill,
                           mutable=["cache"])
        logits, _ = m.apply(
            {"params": params, "cache": vars_["cache"]}, chunk,
            jnp.asarray(prompts.shape[1], jnp.int32), method=m.decode,
            mutable=["cache"])
        return np.asarray(logits)

    np.testing.assert_array_equal(run(True), run(False))
