"""Fused paged-attention decode kernel tests (ISSUE 13). The parity
contract under test: a single-token call is BITWISE identical to
``decode_attention`` over the gathered dense view with ``block_s`` pinned
to the page size — paging is an addressing change, never a numerics
change — and garbage pages (unmapped sentinels, stale contents past the
live length) can never reach the output. Pallas runs in interpreter mode
on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention.decode_attention import decode_attention
from deepspeed_tpu.ops.attention.flash_attention import SUBLANES
from deepspeed_tpu.ops.attention.paged_attention import (
    MAX_QUERY_ROWS,
    paged_decode_attention,
)


def _make_paged(rng, B, KV, D, S, ps, n_free=2, dtype=np.float32):
    """Random dense positions-minor cache (B, KV, D, S) cut into pages at
    a random physical placement. Returns (dense_k, dense_v, k_pages,
    v_pages, table); ``n_free`` extra physical pages stay unmapped so the
    permutation is non-trivial."""
    pages_per_slot = S // ps
    P = B * pages_per_slot + n_free
    dense_k = rng.standard_normal((B, KV, D, S)).astype(dtype)
    dense_v = rng.standard_normal((B, KV, D, S)).astype(dtype)
    perm = rng.permutation(P)[:B * pages_per_slot]
    table = perm.reshape(B, pages_per_slot).astype(np.int32)
    k_pages = np.zeros((P, KV, D, ps), dtype)
    v_pages = np.zeros((P, KV, D, ps), dtype)
    for b in range(B):
        for j in range(pages_per_slot):
            k_pages[table[b, j]] = dense_k[b, :, :, j * ps:(j + 1) * ps]
            v_pages[table[b, j]] = dense_v[b, :, :, j * ps:(j + 1) * ps]
    return dense_k, dense_v, k_pages, v_pages, table


@pytest.mark.parametrize("B,H,KV,D,S,ps", [
    (2, 4, 4, 64, 128, 32),     # MHA
    (2, 8, 2, 64, 128, 16),     # GQA 4x
    (1, 4, 1, 128, 256, 64),    # MQA
])
def test_decode_bitwise_matches_dense_oracle(B, H, KV, D, S, ps):
    """T=1 decode: bitwise-equal to the dense kernel at block_s=ps on
    the gathered view (the serving pool's dense-composition oracle),
    including non-power-of-two live lengths."""
    rng = np.random.default_rng(0)
    dense_k, dense_v, k_pages, v_pages, table = _make_paged(
        rng, B, KV, D, S, ps)
    # non-pow2, page-straddling starts; one slot with a single live token
    starts = np.asarray([0, S - ps - 3][:B], np.int32) \
        if B == 2 else np.asarray([S // 2 - 5], np.int32)
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)

    out = paged_decode_attention(q, jnp.asarray(k_pages),
                                 jnp.asarray(v_pages), jnp.asarray(table),
                                 jnp.asarray(starts))
    oracle = decode_attention(q[:, 0], jnp.asarray(dense_k),
                              jnp.asarray(dense_v),
                              jnp.asarray(starts + 1), block_s=ps)
    assert out.shape == (B, 1, H, D)
    np.testing.assert_array_equal(np.asarray(out[:, 0]), np.asarray(oracle))


def test_garbage_pages_and_sentinels_never_reach_output():
    """Dead table entries (sentinel = P) and garbage in unmapped / past-
    length pages must not change a single output bit — masking is by
    length, and dead grid steps clamp to the last live page."""
    rng = np.random.default_rng(1)
    B, H, KV, D, S, ps = 2, 4, 2, 64, 128, 32
    _, _, k_pages, v_pages, table = _make_paged(rng, B, KV, D, S, ps)
    starts = np.asarray([ps + 5, 2 * ps - 1], np.int32)  # 2 live pages each
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    P = k_pages.shape[0]

    clean = paged_decode_attention(q, jnp.asarray(k_pages),
                                   jnp.asarray(v_pages), jnp.asarray(table),
                                   jnp.asarray(starts))

    # poison every page past each slot's live range and point the dead
    # table entries at the unmapped sentinel (the pool's discipline for
    # freed pages); large-but-finite garbage — exp(NEG_INF - m) == 0
    # exactly, so masked columns contribute exactly nothing
    dirty_k, dirty_v, dirty_t = (k_pages.copy(), v_pages.copy(),
                                 table.copy())
    live_pages = (starts + 1 + ps - 1) // ps
    mapped_live = {int(table[b, j])
                   for b in range(B) for j in range(live_pages[b])}
    for p in range(P):
        if p not in mapped_live:
            dirty_k[p] = 1e4
            dirty_v[p] = -1e4
    for b in range(B):
        dirty_t[b, live_pages[b]:] = P          # unmapped sentinel
    # stale columns past the live length INSIDE the last live page too
    for b in range(B):
        last = int(table[b, live_pages[b] - 1])
        col = (starts[b] + 1) % ps
        if col:
            dirty_k[last, :, :, col:] = 1e4
            dirty_v[last, :, :, col:] = -1e4

    dirty = paged_decode_attention(q, jnp.asarray(dirty_k),
                                   jnp.asarray(dirty_v),
                                   jnp.asarray(dirty_t),
                                   jnp.asarray(starts))
    np.testing.assert_array_equal(np.asarray(clean), np.asarray(dirty))


def _reference_rows(q, dense_k, dense_v, starts):
    """Plain fp32 softmax reference with per-row causal limits: row t of
    slot b attends cache positions [0, starts[b] + t]."""
    B, T, H, D = q.shape
    _, KV, _, S = dense_k.shape
    rep = H // KV
    k = np.repeat(dense_k, rep, axis=1)          # (B, H, D, S)
    v = np.repeat(dense_v, rep, axis=1)
    s = np.einsum("bthd,bhds->bths", q.astype(np.float64),
                  k.astype(np.float64)) / np.sqrt(D)
    pos = np.arange(S)[None, None, None, :]
    limit = (starts[:, None, None, None]
             + np.arange(T)[None, :, None, None])
    s = np.where(pos <= limit, s, -np.inf)
    p = np.exp(s - s.max(axis=-1, keepdims=True))
    p = p / p.sum(axis=-1, keepdims=True)
    return np.einsum("bths,bhds->bthd", p, v.astype(np.float64))


@pytest.mark.parametrize("T", [2, 3, MAX_QUERY_ROWS])
def test_multi_row_verify_matches_reference(T):
    """T>1 (speculative verify): each query row carries its own causal
    limit; numerics match a plain-softmax reference."""
    rng = np.random.default_rng(2)
    B, H, KV, D, S, ps = 2, 4, 2, 64, 128, 16
    dense_k, dense_v, k_pages, v_pages, table = _make_paged(
        rng, B, KV, D, S, ps)
    starts = np.asarray([ps - 1, 3 * ps + 2], np.int32)  # straddle pages
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    out = paged_decode_attention(q, jnp.asarray(k_pages),
                                 jnp.asarray(v_pages), jnp.asarray(table),
                                 jnp.asarray(starts))
    ref = _reference_rows(np.asarray(q), dense_k, dense_v, starts)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4, rtol=1e-4)


def test_row_budget_is_enforced():
    assert MAX_QUERY_ROWS == SUBLANES
    rng = np.random.default_rng(3)
    B, H, KV, D, S, ps = 1, 2, 2, 64, 64, 16
    _, _, k_pages, v_pages, table = _make_paged(rng, B, KV, D, S, ps)
    q = jnp.asarray(
        rng.standard_normal((B, MAX_QUERY_ROWS + 1, H, D)), jnp.float32)
    with pytest.raises(AssertionError, match="query rows"):
        paged_decode_attention(q, jnp.asarray(k_pages),
                               jnp.asarray(v_pages), jnp.asarray(table),
                               jnp.asarray([5], np.int32))


def test_alibi_matches_dense_oracle():
    rng = np.random.default_rng(4)
    B, H, KV, D, S, ps = 2, 4, 4, 64, 128, 32
    dense_k, dense_v, k_pages, v_pages, table = _make_paged(
        rng, B, KV, D, S, ps)
    starts = np.asarray([40, 97], np.int32)
    slopes = jnp.asarray(rng.standard_normal(H) * 0.1, jnp.float32)
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    out = paged_decode_attention(q, jnp.asarray(k_pages),
                                 jnp.asarray(v_pages), jnp.asarray(table),
                                 jnp.asarray(starts), alibi_slopes=slopes)
    oracle = decode_attention(q[:, 0], jnp.asarray(dense_k),
                              jnp.asarray(dense_v),
                              jnp.asarray(starts + 1),
                              alibi_slopes=slopes, block_s=ps)
    np.testing.assert_array_equal(np.asarray(out[:, 0]), np.asarray(oracle))


@pytest.mark.parametrize("packed", [False, True])
def test_quantized_pages_match_dense_oracle(packed):
    """int8 (and int32-packed) page pools with per-column scales: bitwise
    against the dense quantized kernel on the gathered view."""
    from deepspeed_tpu.ops.attention.decode_attention import (
        pack_int8_sublanes,
    )

    rng = np.random.default_rng(5)
    B, H, KV, D, S, ps = 2, 4, 2, 64, 128, 32
    pages_per_slot = S // ps
    P = B * pages_per_slot + 2
    k8 = rng.integers(-127, 128, (P, KV, D, ps)).astype(np.int8)
    v8 = rng.integers(-127, 128, (P, KV, D, ps)).astype(np.int8)
    ks = rng.uniform(0.01, 0.1, (P, KV, ps)).astype(np.float32)
    vs = rng.uniform(0.01, 0.1, (P, KV, ps)).astype(np.float32)
    perm = rng.permutation(P)[:B * pages_per_slot]
    table = perm.reshape(B, pages_per_slot).astype(np.int32)

    def gather(pages):
        # (B, KV, ..., S) dense view through the table
        return np.concatenate([pages[table[:, j]]
                               for j in range(pages_per_slot)], axis=-1)

    starts = np.asarray([S - 3, ps + 7], np.int32)
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    kp, vp = (jnp.asarray(k8), jnp.asarray(v8))
    dk, dv = (jnp.asarray(gather(k8)), jnp.asarray(gather(v8)))
    if packed:
        kp, vp = pack_int8_sublanes(kp), pack_int8_sublanes(vp)
        dk, dv = pack_int8_sublanes(dk), pack_int8_sublanes(dv)
    out = paged_decode_attention(
        q, kp, vp, jnp.asarray(table), jnp.asarray(starts),
        k_scale_pages=jnp.asarray(ks), v_scale_pages=jnp.asarray(vs))
    oracle = decode_attention(
        q[:, 0], dk, dv, jnp.asarray(starts + 1),
        k_scale=jnp.asarray(gather(ks)), v_scale=jnp.asarray(gather(vs)),
        block_s=ps)
    np.testing.assert_array_equal(np.asarray(out[:, 0]), np.asarray(oracle))


def test_jit_and_eager_agree():
    """The kernel under jit (how the pool always calls it) is the same
    function it is eagerly — no trace-time shape surprises."""
    rng = np.random.default_rng(6)
    B, H, KV, D, S, ps = 2, 2, 2, 64, 64, 16
    _, _, k_pages, v_pages, table = _make_paged(rng, B, KV, D, S, ps)
    starts = np.asarray([9, 31], np.int32)
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    args = (q, jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(table), jnp.asarray(starts))
    np.testing.assert_array_equal(
        np.asarray(jax.jit(paged_decode_attention)(*args)),
        np.asarray(paged_decode_attention(*args)))
