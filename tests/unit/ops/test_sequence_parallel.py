"""Sequence-parallelism correctness: ring / Ulysses vs dense reference.

Runs on the virtual 8-device CPU mesh (conftest). The dense reference is
plain softmax attention over the full sequence; the sequence-parallel
implementations must match it to fp32 tolerance, including gradients
(ppermute/all_to_all have transpose rules, so the whole thing is
differentiable end-to-end — that is what makes it usable for training).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention.sequence_parallel import (
    DistributedAttention,
    _dense_attention,
    ring_attention,
    ulysses_attention,
)
from deepspeed_tpu.parallel import initialize_mesh


def _make_qkv(B=2, S=32, H=4, D=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, S, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.fixture
def seq_mesh():
    # data=2 × seq=4 over the 8 CPU devices
    return initialize_mesh(data=2, seq=4)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(seq_mesh, causal):
    q, k, v = _make_qkv()
    want = _dense_attention(q, k, v, causal=causal, scale=1.0 / np.sqrt(8))
    got = ring_attention(q, k, v, mesh=seq_mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(seq_mesh, causal):
    q, k, v = _make_qkv()
    want = _dense_attention(q, k, v, causal=causal, scale=1.0 / np.sqrt(8))
    got = ulysses_attention(q, k, v, mesh=seq_mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match_dense(seq_mesh):
    q, k, v = _make_qkv(B=2, S=16, H=2, D=4, seed=1)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=seq_mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(
            _dense_attention(q, k, v, causal=True, scale=0.5) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=1e-4, atol=1e-4)


def test_distributed_attention_wrapper(seq_mesh):
    q, k, v = _make_qkv(seed=2)
    want = _dense_attention(q, k, v, causal=True, scale=1.0 / np.sqrt(8))
    for strategy in ("ring", "ulysses"):
        attn = DistributedAttention(strategy=strategy, mesh=seq_mesh, causal=True)
        got = attn(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def test_ring_under_jit_sharded_inputs(seq_mesh):
    """ring attention composes with jit + explicitly sharded inputs (the way
    the engine will call it): inputs placed seq-sharded, no resharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = _make_qkv(seed=3)
    sh = NamedSharding(seq_mesh, P("data", "seq", None, None))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, mesh=seq_mesh, causal=True)

    got = f(q, k, v)
    want = _dense_attention(np.asarray(q), np.asarray(k), np.asarray(v),
                            causal=True, scale=1.0 / np.sqrt(8))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
