"""Int8 serving compute: Pallas dequant-GEMM, QuantDense, engine tier.

Parity model: the reference's int8 inference path
(``csrc/quantization/quantize.cu`` + the fused dequant in
``csrc/transformer/inference/csrc/dequantize.cu``) behind
``weight_quantizer.py``. On the CPU suite the kernel runs in interpret
mode; numerics are checked against the jnp dequant-then-dot oracle.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quantization import (
    QuantDense,
    int8_matmul,
    int8_matmul_reference,
    pad_features,
    quantize_columns,
)


def _rand_case(rng, m, k, n):
    w = rng.integers(-127, 128, (k, n), dtype=np.int8)
    s = (rng.random((1, n)) * 0.01 + 1e-3).astype(np.float32)
    x = rng.standard_normal((m, k)).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(w), jnp.asarray(s)


@pytest.mark.parametrize("m,k,n", [
    (8, 256, 384),     # tiled path
    (3, 256, 384),     # M padding
    (5, 100, 384),     # K not a lane multiple -> full-dim K block
    (4, 256, 100),     # N not a lane multiple -> full-dim N block
])
def test_kernel_matches_reference(m, k, n):
    x, w, s = _rand_case(np.random.default_rng(0), m, k, n)
    ref = int8_matmul_reference(x, w, s)
    out = int8_matmul(x, w, s, block_n=128, block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=1e-2,
                               rtol=1e-2)


def test_batched_input_shape():
    x, w, s = _rand_case(np.random.default_rng(1), 6, 128, 256)
    x3 = x.reshape(2, 3, 128)
    out = int8_matmul(x3, w, s, interpret=True)
    assert out.shape == (2, 3, 256)
    flat = int8_matmul(x, w, s, interpret=True)
    np.testing.assert_array_equal(np.asarray(out).reshape(6, 256),
                                  np.asarray(flat))


def test_quantize_columns_roundtrip():
    rng = np.random.default_rng(2)
    w = rng.standard_normal((64, 48)).astype(np.float32)
    q, s = quantize_columns(w)
    assert q.dtype == np.int8 and s.shape == (1, 48)
    back = q.astype(np.float32) * s
    # max per-column error is bounded by half a quant step
    assert np.abs(back - w).max() <= 0.5 * s.max() + 1e-6
    # zero column keeps scale 1.0 (no div-by-zero)
    w[:, 0] = 0.0
    q, s = quantize_columns(w)
    assert s[0, 0] == 1.0 and (q[:, 0] == 0).all()


def test_quant_dense_matches_dense():
    """QuantDense(quantize(W)) tracks nn.Dense(W) within quantization
    error, including a padded feature count."""
    import flax.linen as nn

    rng = np.random.default_rng(3)
    for feats in (256, 200):  # 200 -> padded to 256
        w = (rng.standard_normal((128, feats)) * 0.05).astype(np.float32)
        b = (rng.standard_normal((feats,)) * 0.1).astype(np.float32)
        x = jnp.asarray(rng.standard_normal((4, 128)), jnp.float32)

        dense_out = nn.Dense(feats, dtype=jnp.bfloat16).apply(
            {"params": {"kernel": jnp.asarray(w),
                        "bias": jnp.asarray(b)}}, x)

        n_pad = pad_features(feats)
        wp = np.pad(w, ((0, 0), (0, n_pad - feats)))
        q, s = quantize_columns(wp)
        qd_out = QuantDense(feats, kernel_mode="on").apply(
            {"params": {"kernel": jnp.asarray(q), "scale": jnp.asarray(s),
                        "bias": jnp.asarray(b, jnp.bfloat16)}}, x)
        assert qd_out.shape == dense_out.shape
        err = np.abs(np.asarray(qd_out, np.float32) -
                     np.asarray(dense_out, np.float32))
        assert err.max() < 0.05, err.max()


def test_auto_mode_off_tpu_uses_reference(monkeypatch):
    """kernel_mode='auto' / interpret=None must route to the jnp
    reference on non-TPU backends — interpret-mode Pallas is orders of
    magnitude slower (ADVICE r3 medium)."""
    import importlib

    # the package re-exports the function under the same name; importlib
    # returns the actual submodule
    mod = importlib.import_module(
        "deepspeed_tpu.ops.quantization.int8_matmul")

    def boom(*a, **k):
        raise AssertionError("Pallas kernel invoked on a non-TPU backend")

    monkeypatch.setattr(mod, "_int8_matmul_2d", boom)
    x, w, s = _rand_case(np.random.default_rng(5), 4, 256, 256)
    out = mod.int8_matmul(x, w, s)  # interpret=None, CPU backend
    ref = int8_matmul_reference(x, w, s)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_kernel_plan_vmem_gate():
    """Tile planning: aligned shapes plan normally; a full-dimension
    fallback whose operand tiles exceed the VMEM budget returns None so
    serve-time shapes fall back instead of failing to compile."""
    from deepspeed_tpu.ops.quantization.int8_matmul import (
        VMEM_BUDGET_BYTES,
        _plan_vmem_bytes,
        kernel_plan,
    )

    # aligned: picks divisible 128-multiples, well under budget
    plan = kernel_plan(64, 2048, 2048)
    assert plan is not None
    bm, bk, bn = plan
    assert bk % 128 == 0 and bn % 128 == 0
    assert _plan_vmem_bytes(bm, bk, bn) <= VMEM_BUDGET_BYTES

    # small non-128-multiple N: full-dim block, still under budget
    assert kernel_plan(8, 256, 100) is not None

    # non-128-multiple K forces a full-dim K block of 4000; with a big N
    # block the operand tiles blow the budget -> reference path
    assert kernel_plan(128, 4000, 4096, block_n=512) is None

    # untileable: K too large for the full-dim fallback cap
    assert kernel_plan(8, 5000, 256) is None


def test_engine_int8_compute_tier():
    """dtype=int8 on a TransformerLM swaps Dense -> QuantDense: int8
    kernels in the engine param tree, logits tracking the bf16 engine."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer_lm import (
        TransformerLM,
        transformer_config,
    )

    cfg = transformer_config("llama", vocab_size=256, n_embd=128, n_layer=2,
                             n_head=4, max_seq_len=64)
    model = TransformerLM(cfg)
    ids = jnp.asarray(np.random.default_rng(4).integers(0, 256, (2, 12)))
    params = model.init({"params": jax.random.PRNGKey(0)}, ids,
                        method=model.logits)["params"]

    fp = deepspeed_tpu.init_inference(model, model_parameters=params,
                                      dtype="bfloat16")
    q = deepspeed_tpu.init_inference(model, model_parameters=params,
                                     dtype="int8")
    out_fp = np.asarray(fp.forward(ids), np.float32)
    out_q = np.asarray(q.forward(ids), np.float32)

    n_int8 = sum(1 for leaf in jax.tree_util.tree_leaves(q.params)
                 if leaf.dtype == jnp.int8)
    assert n_int8 > 0, "no int8 kernels in the serving tree"
    # int8-at-rest params are materially smaller than the bf16 tree
    def tree_bytes(t):
        return sum(leaf.size * leaf.dtype.itemsize
                   for leaf in jax.tree_util.tree_leaves(t))
    assert tree_bytes(q.params) < 0.75 * tree_bytes(fp.params)
    agree = (out_fp.argmax(-1) == out_q.argmax(-1)).mean()
    assert agree > 0.9, agree

    toks = q.generate(ids, max_new_tokens=4)
    assert toks.shape == (2, 16)
