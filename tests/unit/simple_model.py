"""Test fixtures: tiny models + data helpers.

Analog of the reference's ``tests/unit/simple_model.py`` (SimpleModel :18,
random_dataloader :257, config helpers :273).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


class SimpleModel(nn.Module):
    """MLP regression model; __call__(batch) -> mse loss (engine convention)."""

    hidden_dim: int = 16
    nlayers: int = 2
    dtype: type = jnp.float32

    @nn.compact
    def __call__(self, batch, deterministic: bool = True):
        x = batch["x"].astype(self.dtype)
        for i in range(self.nlayers):
            x = nn.Dense(self.hidden_dim, dtype=self.dtype, name=f"linear_{i}")(x)
            x = nn.relu(x)
        out = nn.Dense(1, dtype=self.dtype, name="head")(x)
        y = batch["y"].astype(jnp.float32)
        return jnp.mean((out.astype(jnp.float32).squeeze(-1) - y) ** 2)


def random_batch(batch_size: int, dim: int = 16, seed: int = 0):
    rng = np.random.default_rng(seed)
    return {
        "x": rng.normal(size=(batch_size, dim)).astype(np.float32),
        "y": rng.normal(size=(batch_size,)).astype(np.float32),
    }


def random_dataset(n: int, dim: int = 16, seed: int = 0):
    rng = np.random.default_rng(seed)
    return [{"x": rng.normal(size=(dim,)).astype(np.float32),
             "y": rng.normal(size=()).astype(np.float32)} for _ in range(n)]


def tiny_gpt2(vocab: int = 128, n_embd: int = 32, n_layer: int = 2, n_head: int = 2,
              n_positions: int = 32, dtype=jnp.float32, **kw):
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    return GPT2LMHeadModel(GPT2Config(vocab_size=vocab, n_positions=n_positions,
                                      n_embd=n_embd, n_layer=n_layer, n_head=n_head,
                                      dtype=dtype, **kw))


def token_batch(batch_size: int, seq: int = 16, vocab: int = 128, seed: int = 0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab, size=(batch_size, seq)).astype(np.int32)}


def base_config(stage: int = 0, dtype: str = "fp32", micro: int = 2, gas: int = 1,
                world: int = 8, optimizer: str = "Adam", lr: float = 1e-3,
                extra: Optional[dict] = None) -> dict:
    cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": optimizer, "params": {"lr": lr}},
        "zero_optimization": {"stage": stage},
        "steps_per_print": 100,
    }
    if dtype == "bf16":
        cfg["bf16"] = {"enabled": True}
    elif dtype == "fp16":
        cfg["fp16"] = {"enabled": True}
    if extra:
        cfg.update(extra)
    return cfg
