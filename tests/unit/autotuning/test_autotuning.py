"""Autotuner suite — analog of reference ``tests/unit/autotuning/``."""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.autotuning import (
    Autotuner,
    CostModel,
    GridSearchTuner,
    ModelBasedTuner,
    RandomTuner,
)


def _exps(n=6):
    return [{"name": f"e{i}",
             "ds_config": {"train_micro_batch_size_per_gpu": 2 ** i,
                           "zero_optimization": {"stage": i % 4}}}
            for i in range(n)]


class TestTuners:
    def test_gridsearch_finds_best(self):
        scores = {f"e{i}": float(i) for i in range(6)}
        t = GridSearchTuner(_exps(), lambda e: scores[e["name"]],
                            early_stopping=10)
        best, metric = t.tune()
        assert best["name"] == "e5" and metric == 5.0

    def test_early_stopping(self):
        calls = []

        def metric(e):
            calls.append(e["name"])
            return 10.0 if e["name"] == "e0" else 0.0

        t = GridSearchTuner(_exps(), metric, early_stopping=2)
        best, _ = t.tune()
        assert best["name"] == "e0"
        assert len(calls) == 3  # e0 + 2 stale

    def test_random_tuner_deterministic_seed(self):
        scores = {f"e{i}": float(i) for i in range(6)}
        t1 = RandomTuner(_exps(), lambda e: scores[e["name"]],
                         early_stopping=10, seed=3)
        t2 = RandomTuner(_exps(), lambda e: scores[e["name"]],
                         early_stopping=10, seed=3)
        b1, _ = t1.tune()
        b2, _ = t2.tune()
        assert b1["name"] == b2["name"] == "e5"
        # same seed → same visit order
        assert [r[0]["name"] for r in t1.records] == \
            [r[0]["name"] for r in t2.records]

    def test_model_based_tuner(self):
        # metric peaked at mbs=8 → surrogate should still find the max
        def metric(e):
            mbs = e["ds_config"]["train_micro_batch_size_per_gpu"]
            return -abs(mbs - 8)

        t = ModelBasedTuner(_exps(), metric, early_stopping=10,
                            seed_trials=3)
        best, m = t.tune()
        assert best["ds_config"]["train_micro_batch_size_per_gpu"] == 8

    def test_cost_model_boosted_trees_fit_quadratic(self):
        cm = CostModel()
        X = [[float(i), 1.0, 0.0] for i in range(8)]
        y = [-(i - 4.0) ** 2 for i in range(8)]
        cm.fit(X, y)
        assert cm._trees, "8 samples must take the boosted-tree path"
        preds = [cm.predict([float(i), 1.0, 0.0]) for i in range(8)]
        assert int(np.argmax(preds)) == 4

    def test_cost_model_flat_metrics_predict_the_mean(self):
        cm = CostModel()
        X = [[float(i), 1.0, 0.0] for i in range(8)]
        cm.fit(X, [5.0] * 8)  # zero-residual: no trees grown
        assert cm._boosted and not cm._trees
        assert abs(cm.predict([3.0, 1.0, 0.0]) - 5.0) < 1e-9

    def test_cost_model_boosted_trees_fit_nonsmooth_interaction(self):
        """The GBDT surrogate must rank a cliff + interaction surface a
        quadratic cannot represent (e.g. OOM cliff at mbs>8 composed with
        a zero-stage interaction)."""
        grid = [(float(m), float(s)) for m in range(1, 13) for s in (0., 2.)]

        def truth(m, s):
            if m > 8:            # OOM cliff
                return -100.0
            return m * (2.0 if s == 2.0 else 1.0)  # stage-2 doubles gain

        X = [[m, 1.0, s] for m, s in grid]
        y = [truth(m, s) for m, s in grid]
        cm = CostModel()
        cm.fit(X, y)
        preds = {(m, s): cm.predict([m, 1.0, s]) for m, s in grid}
        best = max(preds, key=preds.get)
        assert best == (8.0, 2.0), best
        # the cliff must be learned: any mbs>8 predicts far below the best
        assert all(preds[(m, s)] < preds[(8.0, 2.0)] - 50
                   for m, s in grid if m > 8)
        assert cm._trees, "expected the boosted-tree path, not the fallback"

    def test_cost_model_quadratic_fallback_small_sample(self):
        cm = CostModel()
        X = [[float(i), 1.0, 0.0] for i in range(4)]  # < min_tree_samples
        cm.fit(X, [float(2 * i) for i in range(4)])
        assert not cm._trees and cm._w is not None
        assert abs(cm.predict([5.0, 1.0, 0.0]) - 10.0) < 1e-6


class TestAutotunerInProcess:
    def _factories(self):
        from tests.unit.simple_model import SimpleModel

        def model_factory():
            return SimpleModel(hidden_dim=16)

        def batch_factory(batch_size):
            rng = np.random.default_rng(0)
            return {"x": rng.standard_normal((batch_size, 16),
                                             dtype=np.float32),
                    "y": rng.standard_normal((batch_size,),
                                             dtype=np.float32)}

        return model_factory, batch_factory

    def test_generate_experiments_grid(self):
        mf, bf = self._factories()
        at = Autotuner(mf, bf,
                       base_config={"optimizer": {"type": "Adam",
                                                  "params": {"lr": 1e-3}}},
                       autotuning_config={
                           "num_tuning_micro_batch_sizes": 2,
                           "max_train_micro_batch_size_per_gpu": 4})
        exps = at._generate_experiments()
        assert len(exps) == 4 * 2
        stages = {e["ds_config"]["zero_optimization"]["stage"] for e in exps}
        assert stages == {0, 1, 2, 3}

    def test_model_info(self):
        mf, bf = self._factories()
        at = Autotuner(mf, bf)
        info = at.model_info()
        assert info["num_params"] > 0
        assert info["param_mem_per_stage"][3] < \
            info["param_mem_per_stage"][0]

    def test_tune_end_to_end(self, tmp_path):
        mf, bf = self._factories()
        at = Autotuner(
            mf, bf,
            base_config={"optimizer": {"type": "Adam",
                                       "params": {"lr": 1e-3}},
                         "steps_per_print": 1000},
            autotuning_config={
                "num_tuning_micro_batch_sizes": 2,
                "max_train_micro_batch_size_per_gpu": 8,
                "start_profile_step": 1, "end_profile_step": 3,
                "results_dir": str(tmp_path / "results")})
        best = at.tune(stages=[0, 1])
        assert best and "ds_config" in best
        assert os.path.exists(tmp_path / "results" /
                              "autotuning_results.json")
        assert os.path.exists(tmp_path / "results" / "best_config.json")
        with open(tmp_path / "results" / "best_config.json") as f:
            cfg = json.load(f)
        assert "train_micro_batch_size_per_gpu" in cfg


def test_engine_writes_metric_file(tmp_path):
    import deepspeed_tpu as ds
    from tests.unit.simple_model import SimpleModel, random_batch

    metric_path = str(tmp_path / "metric.json")
    config = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "autotuning": {"enabled": True, "metric_path": metric_path,
                       "start_profile_step": 1, "end_profile_step": 3},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=16),
                                    config=config)
    b = random_batch(engine.train_batch_size())
    for _ in range(4):
        engine.train_batch(batch=b)
    with open(metric_path) as f:
        m = json.load(f)
    assert m["throughput"] > 0
    assert m["steps"] == 2


# ---------------------------------------------------------------------------
# round 2: ResourceManager — real subprocess experiments, measured metrics
# ---------------------------------------------------------------------------
TOY_SCRIPT = '''
import os, numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import flax.linen as nn
import deepspeed_tpu as ds


class Toy(nn.Module):
    @nn.compact
    def __call__(self, batch):
        x = batch["x"]
        y = nn.Dense(16)(jax.nn.relu(nn.Dense(16)(x)))
        return jnp.mean((y - batch["y"]) ** 2)


# config comes from DS_AUTOTUNING_CONFIG (engine reads the env override)
engine, _, _, _ = ds.initialize(model=Toy(), config={
    "train_micro_batch_size_per_gpu": 1,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
rng = np.random.default_rng(0)
batch = {"x": rng.standard_normal((engine.train_batch_size(), 8)).astype("float32"),
         "y": rng.standard_normal((engine.train_batch_size(), 16)).astype("float32")}
for _ in range(64):  # DS_AUTOTUNING_EXIT ends the run after the window
    engine.train_batch(batch=batch)
'''


class TestResourceManager:
    def test_node_reservations(self):
        from deepspeed_tpu.autotuning import Node

        n = Node("h1", 2)
        a = n.reserve(1)
        b = n.reserve(1)
        assert a == [0] and b == [1]
        assert n.reserve(1) is None
        n.release(a)
        assert n.reserve(1) == [0]

    def test_end_to_end_real_experiments(self, tmp_path):
        """VERDICT done-criterion: an end-to-end tune over a toy model with
        REAL measured metrics — each experiment is a subprocess run of the
        user script; throughput comes from the engine's profile window."""
        from deepspeed_tpu.autotuning import ResourceManager

        script = tmp_path / "train_toy.py"
        script.write_text(TOY_SCRIPT)
        exps = []
        for stage in (0, 1):
            exps.append({
                "name": f"z{stage}",
                "ds_config": {
                    "train_micro_batch_size_per_gpu": 2,
                    "zero_optimization": {"stage": stage},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "autotuning": {"enabled": True,
                                   "start_profile_step": 2,
                                   "end_profile_step": 4},
                },
            })
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        manager = ResourceManager(
            hosts={"localhost": 1},
            results_dir=str(tmp_path / "results"),
            exps_dir=str(tmp_path / "exps"),
            env={"JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": repo_root + os.pathsep +
                 os.environ.get("PYTHONPATH", "")})
        manager.schedule_experiments(exps)
        finished = manager.run(str(script), [])
        assert len(finished) == 2
        for exp in finished.values():
            assert exp["returncode"] == 0, \
                open(os.path.join(exp["result_dir"], "stderr.log")).read()[-2000:]
            assert exp["metrics"] is not None
            assert exp["metrics"]["throughput"] > 0
            assert exp["metrics"]["steps"] == 2
        best = manager.best("throughput")
        assert best is not None
        assert best["name"] in ("z0", "z1")
        assert "autotuning" not in best["ds_config"]

        # resume: re-scheduling the same experiments skips both runs
        m2 = ResourceManager(
            hosts={"localhost": 1},
            results_dir=str(tmp_path / "results"),
            exps_dir=str(tmp_path / "exps"))
        m2.schedule_experiments(exps)
        assert not m2.experiment_queue
        assert len(m2.finished) == 2

    def test_arg_mappings_rewrite(self, tmp_path):
        from deepspeed_tpu.autotuning.scheduler import _get_by_dotted_key

        cfg = {"train_micro_batch_size_per_gpu": 4,
               "zero_optimization": {"stage": 2}}
        assert _get_by_dotted_key(cfg, "train_micro_batch_size_per_gpu") == 4
        assert _get_by_dotted_key(cfg, "zero_optimization.stage") == 2
        assert _get_by_dotted_key(cfg, "zero_optimization.missing") is None
