"""Telemetry subsystem tests: ring-buffer tracer semantics (wraparound,
thread safety, Perfetto-loadable export), metrics registry + Prometheus
exposition, recompile watchdog attribution/strict mode, dispatch-aware
timers, the JSONL monitor sink, and pipeline schedule tracing."""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.telemetry import (
    MetricsRegistry,
    RecompileAfterWarmupError,
    RecompileWatchdog,
    TimelineStore,
    Tracer,
    abstract_signature,
)


class _FakeMonitor:
    enabled = True

    def __init__(self):
        self.events = []

    def write_events(self, events):
        self.events.extend(events)


# ----------------------------------------------------------------------
# tracer
# ----------------------------------------------------------------------
class TestTracer:
    def test_ring_buffer_wraparound_keeps_newest_oldest_first(self):
        tr = Tracer(capacity=8)
        for i in range(20):
            tr.instant(f"ev-{i}")
        evs = tr.events()
        assert len(evs) == 8
        assert tr.events_total == 20
        # the window holds the 8 newest events, oldest first
        assert [e["name"] for e in evs] == [f"ev-{i}" for i in range(12, 20)]
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)

    def test_span_records_duration_and_attrs(self):
        tr = Tracer()
        with tr.span("work", phase="x") as sp:
            sp.set(extra=3)
        (ev,) = tr.events()
        assert ev["ph"] == "X" and ev["name"] == "work"
        assert ev["dur"] >= 0
        assert ev["args"] == {"phase": "x", "extra": 3}

    def test_span_records_error_class_on_exception(self):
        tr = Tracer()
        with pytest.raises(ValueError):
            with tr.span("boom"):
                raise ValueError("nope")
        (ev,) = tr.events()
        assert ev["args"]["error"] == "ValueError"

    def test_disabled_tracer_is_null(self):
        tr = Tracer(enabled=False)
        with tr.span("x") as sp:
            sp.set(a=1)  # null span absorbs attrs
        tr.instant("y")
        tr.counter("z", v=1)
        tr.async_begin("c", "n", 0)
        tr.flow("s", "f", 0)
        assert tr.events() == [] and tr.events_total == 0

    def test_trace_decorator(self):
        tr = Tracer()

        @tr.trace("decorated")
        def f(x):
            return x + 1

        assert f(1) == 2
        assert tr.events()[0]["name"] == "decorated"

    def test_thread_safety_under_concurrent_spans(self):
        tr = Tracer(capacity=100_000)
        n_threads, n_spans = 8, 200
        errors = []

        def worker(k):
            try:
                for i in range(n_spans):
                    with tr.span(f"t{k}", i=i):
                        pass
                    tr.counter(f"c{k}", v=i)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert tr.events_total == n_threads * n_spans * 2
        assert len(tr.events()) == n_threads * n_spans * 2

    def test_chrome_export_schema(self, tmp_path):
        tr = Tracer(process_name="test-proc")
        with tr.span("outer", step=1):
            with tr.span("inner"):
                pass
        tr.counter("slots", live=2)
        tr.async_begin("request", "req-1", 1, event="submitted")
        tr.async_instant("request", "first_token", 1)
        tr.async_end("request", "req-1", 1)
        tr.flow("s", "req", 1)
        tr.flow("f", "req", 1)

        path = tmp_path / "trace.json"
        n = tr.export(str(path))
        doc = json.loads(path.read_text())  # valid JSON round-trip
        evs = doc["traceEvents"]
        assert n == len(evs)
        phs = {e["ph"] for e in evs}
        assert {"X", "C", "b", "n", "e", "s", "f", "M"} <= phs
        for e in evs:
            assert isinstance(e["name"], str) and "pid" in e and "tid" in e
            if e["ph"] != "M":
                assert e["ts"] >= 0  # µs, normalized to window start
        flow_f = [e for e in evs if e["ph"] == "f"]
        assert flow_f and all(e["bp"] == "e" for e in flow_f)
        names = [e["args"]["name"] for e in evs if e["name"] == "process_name"]
        assert names == ["test-proc"]
        assert doc["otherData"]["events_total"] == tr.events_total
        assert doc["otherData"]["dropped"] == 0

    def test_export_reports_dropped_after_wrap(self, tmp_path):
        tr = Tracer(capacity=4)
        for i in range(10):
            tr.instant(f"e{i}")
        path = tmp_path / "t.json"
        tr.export(str(path))
        doc = json.loads(path.read_text())
        assert doc["otherData"]["dropped"] == 6

    def test_clear_and_capacity_validation(self):
        tr = Tracer(capacity=4)
        tr.instant("a")
        tr.clear()
        assert tr.events() == [] and tr.events_total == 0
        with pytest.raises(ValueError, match="capacity"):
            Tracer(capacity=0)


# ----------------------------------------------------------------------
# registry
# ----------------------------------------------------------------------
class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        c = reg.counter("serving/finished")
        c.inc()
        c.inc(2)
        assert c.value == 3
        with pytest.raises(ValueError, match="negative"):
            c.inc(-1)
        assert reg.counter("serving/finished") is c  # idempotent

        g = reg.gauge("serving/live")
        g.set(5)
        g.dec(2)
        g.inc()
        assert g.value == 4

        h = reg.histogram("serving/ttft_ms")
        for v in (0.5, 3, 30, 30, 9999):
            h.observe(v)
        assert h.count == 5 and h.total == pytest.approx(10062.5)
        assert h.quantile(0.5) <= h.quantile(0.99)

    def test_type_conflict_raises(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError, match="already registered"):
            reg.gauge("x")

    def test_snapshot_flattens_histograms(self):
        reg = MetricsRegistry()
        reg.counter("a").inc(2)
        reg.histogram("lat_ms").observe(7)
        snap = reg.snapshot()
        assert snap["a"] == 2
        assert snap["lat_ms/count"] == 1
        assert snap["lat_ms/sum"] == 7
        assert "lat_ms/p50" in snap and "lat_ms/p99" in snap

    def test_prometheus_exposition(self):
        reg = MetricsRegistry()
        reg.counter("serving/finished").inc(3)
        reg.gauge("serving/live").set(2)
        h = reg.histogram("serving/ttft_ms", buckets=(1.0, 10.0))
        h.observe(0.5)
        h.observe(5.0)
        h.observe(500.0)
        text = reg.to_prometheus()
        assert "# TYPE serving_finished counter" in text
        assert "serving_finished 3" in text
        assert "# TYPE serving_live gauge" in text
        assert "# TYPE serving_ttft_ms histogram" in text
        # buckets are cumulative, ending at the total count
        assert 'serving_ttft_ms_bucket{le="1"} 1' in text
        assert 'serving_ttft_ms_bucket{le="10"} 2' in text
        assert 'serving_ttft_ms_bucket{le="+Inf"} 3' in text
        assert "serving_ttft_ms_sum 505.5" in text
        assert "serving_ttft_ms_count 3" in text

    def test_publish_flushes_to_monitor(self):
        reg = MetricsRegistry()
        reg.counter("serving/finished").inc(4)
        reg.gauge("serving/live").set(1)
        mon = _FakeMonitor()
        n = reg.publish(mon, step=17)
        assert n == 2 == len(mon.events)
        tags = [t for t, _, _ in mon.events]
        assert tags == sorted(tags)
        assert all(t.startswith("telemetry/") for t in tags)
        assert all(s == 17 for _, _, s in mon.events)
        # disabled / missing monitors are a safe no-op
        assert reg.publish(None, step=1) == 0

        class _Off:
            enabled = False

        assert reg.publish(_Off(), step=1) == 0


# ----------------------------------------------------------------------
# timeline store
# ----------------------------------------------------------------------
class TestTimelineStore:
    def test_record_get_and_eviction(self):
        tl = TimelineStore(capacity=2)
        tl.record(1, "submitted", prompt_len=4)
        tl.record(1, "finished", terminal=True, reason="length")
        tl.record(2, "submitted")
        tl.record(3, "submitted")  # evicts request 1
        assert tl.get(1) is None
        assert tl.events_of(2) == ["submitted"]
        assert len(tl) == 2
        ev = tl.get(3)[0]
        assert ev["event"] == "submitted" and ev["t_ns"] > 0

    def test_mirrors_async_track_into_tracer(self):
        tr = Tracer()
        tl = TimelineStore(tracer=tr)
        tl.record(7, "submitted", prompt_len=4)
        tl.record(7, "first_token")
        tl.record(7, "finished", terminal=True, reason="length")
        phs = [e["ph"] for e in tr.events()]
        assert phs[0] == "b" and phs[-1] == "e" and "n" in phs
        assert all(e["cat"] == "request" and e["id"] == 7
                   for e in tr.events())

    def test_capacity_validation(self):
        with pytest.raises(ValueError, match="capacity"):
            TimelineStore(capacity=0)


# ----------------------------------------------------------------------
# recompile watchdog
# ----------------------------------------------------------------------
class _Owner:
    def __init__(self):
        self.fn = jax.jit(lambda x: x * 2)


class TestWatchdog:
    def test_attributes_cache_growth_and_warmup_split(self):
        reg = MetricsRegistry()
        tr = Tracer()
        mon = _FakeMonitor()
        owner = _Owner()
        wd = RecompileWatchdog(registry=reg, tracer=tr, monitor=mon,
                               step_fn=lambda: 42)
        assert wd.attach(owner, "fn", name="fn") is not None
        assert wd.attach(owner, "missing") is None

        owner.fn(jnp.ones((4,)))          # first compile: warmup
        assert wd.warmup_recompiles == 1 and wd.recompiles == 0
        wd.end_warmup()
        assert wd.warmed
        owner.fn(jnp.ones((4,)))          # cache hit: no recompile
        assert wd.recompiles == 0
        owner.fn(jnp.ones((8,)))          # forced shape change
        assert wd.recompiles == 1
        assert reg.counter("telemetry/recompiles").value == 1
        assert reg.counter("telemetry/recompiles_warmup").value == 1
        ev = wd.events[-1]
        assert ev["program"] == "fn" and not ev["warmup"]
        assert "float32[8]" in ev["signature"]
        assert ("telemetry/recompile", 1.0, 42) in mon.events
        assert any(e["name"] == "telemetry/recompile" for e in tr.events())
        assert wd.summary()["programs"] == ["fn"]

    def test_attach_is_shared_across_watchdogs(self):
        owner = _Owner()
        wd1 = RecompileWatchdog()
        wd2 = RecompileWatchdog()
        p1 = wd1.attach(owner, "fn")
        p2 = wd2.attach(owner, "fn")
        assert p1 is p2  # never double-wrapped
        wd1.end_warmup()
        wd2.end_warmup()
        owner.fn(jnp.ones((3,)))
        assert wd1.recompiles == 1 and wd2.recompiles == 1
        # attribute passthrough: jit internals stay reachable
        assert owner.fn._cache_size() >= 1

    def test_tolerates_plain_callables(self):
        owner = _Owner()
        owner.fn = lambda x: x  # tests inject bare lambdas
        wd = RecompileWatchdog()
        wd.attach(owner, "fn")
        wd.end_warmup()
        assert owner.fn(5) == 5
        assert wd.recompiles == 0

    def test_strict_mode_raises_once_per_recompile(self):
        owner = _Owner()
        wd = RecompileWatchdog(strict=True)
        wd.attach(owner, "fn")
        owner.fn(jnp.ones((4,)))
        wd.check()                        # warmup compiles never raise
        wd.end_warmup()
        owner.fn(jnp.ones((16,)))
        with pytest.raises(RecompileAfterWarmupError, match="fn"):
            wd.check()
        wd.check()                        # already reported: no re-raise
        owner.fn(jnp.ones((32,)))
        with pytest.raises(RecompileAfterWarmupError):
            wd.check()

    def test_abstract_signature(self):
        sig = abstract_signature(
            (np.zeros((2, 3), np.float32), 5), {"flag": True})
        assert sig == "(float32[2,3], 5, flag=True)"


# ----------------------------------------------------------------------
# timers
# ----------------------------------------------------------------------
class TestTimers:
    def test_barrier_timer_requires_block_on(self):
        from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer

        timers = SynchronizedWallClockTimer()
        t = timers("strict", barrier=True)
        assert timers("strict") is t  # name lookup is stable
        t.start()
        with pytest.raises(RuntimeError, match="block_on"):
            t.stop()
        out = jax.jit(lambda x: x + 1)(jnp.ones((4,)))
        t.stop(block_on=out)
        assert len(t.records) == 1 and t.records[0] >= 0
        # elapsed() peeks via stop(record=False): legal on barrier timers
        t.start()
        assert t.elapsed() >= 0

    def test_plain_timer_and_publish(self):
        from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer

        timers = SynchronizedWallClockTimer()
        t = timers("fwd")
        for _ in range(3):
            t.start()
            t.stop()
        reg = MetricsRegistry()
        assert timers.publish(reg) == 3
        assert reg.histogram("timer/fwd_ms").count == 3
        assert timers.publish(reg) == 0  # drained: no double counting

    def test_throughput_timer_block_on(self):
        from deepspeed_tpu.utils.timer import ThroughputTimer

        tt = ThroughputTimer(batch_size=2, start_step=0)
        out = jax.jit(lambda x: x * 3)(jnp.ones((4,)))
        for _ in range(3):
            tt.start()
            tt.stop(global_step=True, report_speed=False, block_on=out)
        assert tt.avg_samples_per_sec() > 0


# ----------------------------------------------------------------------
# JSONL monitor sink
# ----------------------------------------------------------------------
class TestJSONLMonitor:
    def test_sink_writes_loadable_lines(self, tmp_path):
        from deepspeed_tpu.monitor.monitor import JSONLMonitor
        from deepspeed_tpu.runtime.config import JSONLConfig

        cfg = JSONLConfig(enabled=True, output_path=str(tmp_path),
                          job_name="job")
        mon = JSONLMonitor(cfg)
        mon.write_events([("serving/ttft_ms", 6.7, 3),
                          ("telemetry/recompile", 1, 4)])
        mon.write_events([("serving/ttft_ms", 7.0, 5)])
        lines = [json.loads(ln) for ln in
                 open(mon.path).read().splitlines()]
        assert len(lines) == 3
        for rec in lines:
            assert set(rec) == {"tag", "value", "step", "time"}
            assert isinstance(rec["value"], float)
            assert isinstance(rec["step"], int)
        assert lines[0]["tag"] == "serving/ttft_ms"
        assert lines[1]["value"] == 1.0

    def test_monitor_master_fans_out_to_jsonl(self, tmp_path):
        from deepspeed_tpu.monitor.monitor import MonitorMaster
        from deepspeed_tpu.runtime.config import MonitorConfig

        cfg = MonitorConfig(jsonl={"enabled": True,
                                   "output_path": str(tmp_path),
                                   "job_name": "j"})
        assert cfg.enabled  # jsonl alone flips the master switch
        master = MonitorMaster(cfg)
        assert master.jsonl_monitor is not None
        master.write_events([("a/b", 1.0, 0)])
        rec = json.loads(open(master.jsonl_monitor.path).readline())
        assert rec["tag"] == "a/b"

    def test_disabled_by_default(self):
        from deepspeed_tpu.runtime.config import MonitorConfig

        cfg = MonitorConfig()
        assert not cfg.jsonl.enabled and not cfg.enabled


# ----------------------------------------------------------------------
# pipeline schedule tracing
# ----------------------------------------------------------------------
class TestScheduleTrace:
    def test_train_schedule_trace(self, tmp_path):
        from deepspeed_tpu.runtime.pipe.schedule import (
            TrainSchedule, export_schedule_trace, schedule_trace)

        doc = schedule_trace(TrainSchedule, micro_batches=4, stages=2)
        evs = doc["traceEvents"]
        tracks = {e["args"]["name"] for e in evs
                  if e["name"] == "thread_name"}
        assert tracks == {"stage 0", "stage 1"}
        names = {e["name"] for e in evs if e["ph"] == "X"}
        assert {"ForwardPass", "BackwardPass", "OptimizerStep"} <= names
        # every stage runs each micro-batch forward exactly once
        for stage in (0, 1):
            fwd = [e for e in evs if e["ph"] == "X" and e["tid"] == stage
                   and e["name"] == "ForwardPass"]
            assert len(fwd) == 4
        path = tmp_path / "sched.json"
        n = export_schedule_trace(TrainSchedule, 4, 2, str(path))
        assert n == len(json.loads(path.read_text())["traceEvents"])
