"""Efficiency/goodput telemetry unit tests (host-only, no engine):
quantile-digest accuracy against numpy on adversarial distributions,
window rotation and merge semantics, SLO goodput + burn-rate alerting,
and the flight-recorder ring + post-mortem file schema."""

import json
import math
import os

import numpy as np
import pytest

from deepspeed_tpu.telemetry.flight_recorder import (POST_MORTEM_KEYS,
                                                     SCHEMA_VERSION,
                                                     FlightRecorder)
from deepspeed_tpu.telemetry.slo import (QuantileDigest, SLOConfig,
                                         SLOTargets, SLOTracker,
                                         WindowedQuantiles)


# -- QuantileDigest ----------------------------------------------------
# the digest's guarantee is RELATIVE error (geometric bucket midpoint),
# so every accuracy assertion is on |est/true - 1|. rel_error=0.01
# bounds the bucket half-width at 1%; rank rounding vs numpy's
# interpolation adds at most one bucket, hence the 3% tolerance.
_DISTS = {
    "lognormal": lambda g: g.lognormal(mean=3.0, sigma=1.5, size=20_000),
    "pareto": lambda g: (1.0 + g.pareto(a=1.5, size=20_000)) * 10.0,
    # unequal modes so p50/p90/p99 land INSIDE a mode — a quantile at
    # the exact mode boundary is degenerate (numpy interpolates across
    # the gap, a rank-based digest picks a side; both are defensible)
    "bimodal": lambda g: np.concatenate([
        g.normal(5.0, 0.5, size=9_000),
        g.normal(5_000.0, 250.0, size=11_000)]),
    "uniform_wide": lambda g: g.uniform(0.05, 9e6, size=20_000),
}


@pytest.mark.parametrize("name", sorted(_DISTS))
@pytest.mark.parametrize("q", [0.5, 0.9, 0.99])
def test_digest_accuracy_vs_numpy(name, q):
    vals = np.clip(_DISTS[name](np.random.default_rng(7)), 1e-2, 1e7)
    d = QuantileDigest(min_value=1e-2, max_value=1e7, rel_error=0.01)
    for v in vals:
        d.add(float(v))
    true = float(np.quantile(vals, q))
    assert abs(d.quantile(q) / true - 1.0) < 0.03, \
        f"{name} p{int(q * 100)}: digest={d.quantile(q)} numpy={true}"


def test_digest_constant_stream_is_exact():
    d = QuantileDigest()
    for _ in range(1000):
        d.add(42.0)
    # min/max clamping collapses the bucket midpoint to the only value
    for q in (0.01, 0.5, 0.99):
        assert d.quantile(q) == 42.0


def test_digest_edge_inputs():
    d = QuantileDigest(min_value=1e-2, max_value=1e3)
    d.add(float("nan"))          # dropped
    assert d.count == 0
    d.add(-5.0)                  # clamped to 0 -> bottom bucket
    d.add(0.0)
    d.add(1e9)                   # above max -> top bucket, clamped answer
    assert d.count == 3
    assert d.quantile(0.99) <= 1e9


def test_digest_merge_equals_union_stream():
    g = np.random.default_rng(11)
    a_vals = g.lognormal(2.0, 1.0, size=5_000)
    b_vals = g.lognormal(4.0, 0.5, size=5_000)
    a = QuantileDigest()
    b = QuantileDigest()
    u = QuantileDigest()
    for v in a_vals:
        a.add(float(v))
        u.add(float(v))
    for v in b_vals:
        b.add(float(v))
        u.add(float(v))
    a.merge(b)
    assert a.count == u.count == 10_000
    for q in (0.5, 0.9, 0.99):
        assert a.quantile(q) == u.quantile(q)


def test_digest_merge_rejects_mismatched_params():
    with pytest.raises(ValueError):
        QuantileDigest(rel_error=0.01).merge(QuantileDigest(rel_error=0.05))


def test_digest_memory_is_fixed():
    d = QuantileDigest(min_value=1e-2, max_value=1e7, rel_error=0.01)
    n0 = len(d.counts)
    assert n0 == int(math.ceil(
        math.log(1e9) / math.log(1.02))) + 1
    for v in np.random.default_rng(3).lognormal(3, 2, size=50_000):
        d.add(float(v))
    assert len(d.counts) == n0        # no growth, ever


# -- WindowedQuantiles -------------------------------------------------
def test_window_rotation_expires_old_values():
    wq = WindowedQuantiles(windows=4)
    for _ in range(100):
        wq.add(1000.0)                # a spike in the oldest window
    assert wq.quantile(0.5) == pytest.approx(1000.0, rel=0.03)
    for _ in range(3):
        wq.rotate()
        for _ in range(100):
            wq.add(1.0)
    # spike window still in the ring: p99 sees it
    assert wq.quantile(0.99) == pytest.approx(1000.0, rel=0.03)
    wq.rotate()                       # ...now recycled
    for _ in range(100):
        wq.add(1.0)
    assert wq.quantile(0.99) == pytest.approx(1.0, rel=0.03)
    assert wq.count == 400


# -- SLOConfig / SLOTracker --------------------------------------------
def test_slo_config_resolve_forms():
    assert SLOConfig.resolve(None) is None
    assert SLOConfig.resolve(False) is None
    assert SLOConfig.resolve(True).classes["default"].ttft_ms == 500.0
    cfg = SLOConfig.resolve({"ttft_ms": 50.0, "window_steps": 16,
                             "classes": {"batch": {"ttft_ms": None,
                                                   "gap_ms": 1000.0}}})
    assert cfg.classes["default"].ttft_ms == 50.0
    assert cfg.classes["default"].gap_ms == 200.0     # default kept
    assert cfg.classes["batch"].ttft_ms is None
    assert cfg.window_steps == 16
    assert SLOConfig.resolve(cfg) is cfg
    with pytest.raises(TypeError):
        SLOConfig.resolve(123)


def test_slo_goodput_counts_failures_against():
    t = SLOTracker({"ttft_ms": 100.0, "gap_ms": None})
    for _ in range(8):
        t.observe_admitted()
    for _ in range(6):
        t.observe_finish(ttft_s=0.010)              # within
    t.observe_finish(ttft_s=5.0)                    # TTFT blown
    t.observe_finish(ttft_s=0.010, ok=False)        # fast but failed
    assert t.goodput() == pytest.approx(6 / 8)
    snap = t.snapshot()
    assert snap["admitted"] == 8 and snap["good"] == 6
    assert snap["ttft_p50_ms"] == pytest.approx(10.0, rel=0.03)


def test_slo_burn_rate_alerting_and_reset():
    t = SLOTracker({"ttft_ms": 100.0, "gap_ms": None, "window_steps": 4,
                    "windows": 4, "goodput_target": 0.9,
                    "warn_burn": 2.0, "page_burn": 5.0})
    # every admitted request blows its SLO -> goodput 0, burn 1/0.1 = 10
    for step in range(16):
        t.observe_admitted()
        t.observe_finish(ttft_s=9.0)
        t.on_step(step)
    assert t.alert_state == "page"
    assert t.burn_short >= 5.0 and t.burn_long >= 5.0
    assert t.rotations == 4
    t.reset()
    assert t.alert_state == "ok" and t.goodput() == 1.0
    assert t.overhead_ns == 0
    # healthy traffic keeps it ok
    for step in range(8):
        t.observe_admitted()
        t.observe_finish(ttft_s=0.010)
        t.on_step(step)
    assert t.alert_state == "ok"


def test_slo_per_class_targets():
    t = SLOTracker({"ttft_ms": 100.0, "gap_ms": None,
                    "classes": {"batch": SLOTargets(ttft_ms=None,
                                                    gap_ms=None)}})
    t.observe_admitted("batch")
    assert t.observe_finish(ttft_s=99.0, cls="batch")   # no targets: good
    t.observe_admitted()
    assert not t.observe_finish(ttft_s=99.0)            # default: blown
    assert t.snapshot()["per_class"]["batch"]["good"] == 1


def test_slo_per_class_burn_alerts_are_independent():
    """One class burning must not page the others — the per-class
    two-horizon burn drives the priority scheduler's shedding floor, so
    a batch-tier meltdown paging the interactive tier would shed the
    wrong traffic."""
    t = SLOTracker({"ttft_ms": 100.0, "gap_ms": None, "window_steps": 4,
                    "windows": 4, "goodput_target": 0.9,
                    "warn_burn": 2.0, "page_burn": 5.0})
    for step in range(16):
        t.observe_admitted(cls="interactive")
        t.observe_finish(ttft_s=9.0, cls="interactive")   # always blown
        t.observe_admitted(cls="batch")
        t.observe_finish(ttft_s=0.010, cls="batch")       # always within
        t.on_step(step)
    assert t.class_alert("interactive") == "page"
    assert t.class_alert("batch") == "ok"
    assert t.class_alert("never_seen") == "ok"
    short, long = t.class_burns["interactive"]
    assert short >= 5.0 and long >= 5.0
    snap = t.snapshot()
    assert snap["per_class"]["interactive"]["alert"] == "page"
    assert snap["per_class"]["batch"]["alert"] == "ok"
    assert snap["per_class"]["batch"]["goodput_window"] == 1.0
    t.reset()
    assert t.class_alerts == {} and t.class_burns == {}


def test_slo_observe_cancel_is_goodput_neutral():
    """A cancelled request withdraws its admission: goodput must move
    neither up (it never finished well) nor down (the client hanging up
    is not the server's failure)."""
    t = SLOTracker({"ttft_ms": 100.0, "gap_ms": None})
    for _ in range(4):
        t.observe_admitted(cls="interactive")
    for _ in range(3):
        t.observe_finish(ttft_s=0.010, cls="interactive")
    t.observe_cancel(cls="interactive")
    assert t.goodput() == pytest.approx(1.0)
    assert t.cancelled_total == 1
    snap = t.snapshot()
    assert snap["cancelled"] == 1
    assert snap["per_class"]["interactive"]["admitted"] == 3
    # floors at zero even if the admitting window already rotated out
    t2 = SLOTracker(True)
    t2.observe_cancel(cls="ghost")
    assert t2.goodput() == 1.0 and t2.admitted_total == 0


# -- FlightRecorder ----------------------------------------------------
def test_recorder_ring_is_bounded():
    r = FlightRecorder(capacity=8)
    for i in range(100):
        r.record({"step_id": i})
    assert r.records_total == 100
    steps = r.last()
    assert len(steps) == 8
    assert [s["step_id"] for s in steps] == list(range(92, 100))
    assert [s["step_id"] for s in r.last(3)] == [97, 98, 99]


def test_post_mortem_schema_and_dump(tmp_path):
    r = FlightRecorder(capacity=4, dump_dir=str(tmp_path))
    for i in range(6):
        r.record({"step_id": i, "live": i % 2})
    path = r.dump("invariant_violation",
                  error=RuntimeError("free set corrupt"),
                  extra={"violations": ["x"]})
    assert path is not None and os.path.exists(path)
    assert os.path.basename(path) == \
        "postmortem-000-step5-invariant_violation.json"
    with open(path) as f:
        pm = json.load(f)
    assert sorted(pm) == sorted(POST_MORTEM_KEYS)
    assert pm["schema_version"] == SCHEMA_VERSION
    assert pm["reason"] == "invariant_violation"
    assert "free set corrupt" in pm["error"]
    assert pm["records_total"] == 6
    assert [s["step_id"] for s in pm["steps"]] == [2, 3, 4, 5]
    assert pm["extra"] == {"violations": ["x"]}
    assert r.dump_count == 1 and r.dumps == [path]


def test_dump_without_dir_returns_none_and_never_raises(tmp_path):
    r = FlightRecorder(capacity=2)
    r.record({"step_id": 0})
    assert r.dump("stalled") is None
    assert r.dump_count == 0
    # unwritable dir: swallowed, counted, no raise
    blocked = tmp_path / "file-not-dir"
    blocked.write_text("x")
    r.dump_dir = str(blocked)
    assert r.dump("stalled") is None
    assert r.dump_failures == 1


# --------------------------------------------- mesh-normalized peaks
def test_cost_model_peaks_scale_with_mesh_device_count():
    """cost_analysis reports WHOLE-program flops/bytes, so on a sharded
    mesh the MFU/bandwidth denominators must be nominal-peak x
    participating devices — a TP=4 run reporting single-chip MFU > 1.0
    was the bug this normalization fixes."""
    from deepspeed_tpu.telemetry.costs import (ProgramCostModel,
                                               resolve_peaks)

    pf, pb = resolve_peaks()
    one = ProgramCostModel(num_devices=1)
    four = ProgramCostModel(num_devices=4)
    assert one.peak_flops == pytest.approx(pf)
    assert four.peak_flops == pytest.approx(4 * pf)
    assert four.peak_bytes_per_s == pytest.approx(4 * pb)
    assert four.summary()["num_devices"] == 4


def test_cost_model_autodetects_global_mesh():
    """num_devices=None resolves against the installed global mesh at
    construction (1 with no mesh — the single-chip default)."""
    from deepspeed_tpu.parallel import mesh as mesh_mod
    from deepspeed_tpu.telemetry.costs import ProgramCostModel

    assert ProgramCostModel().num_devices == 1  # no mesh installed
    mesh_mod.set_mesh(mesh_mod.initialize_mesh(data=4, model=2))
    try:
        assert ProgramCostModel().num_devices == 8
    finally:
        mesh_mod.reset_mesh()


def test_cost_model_explicit_peaks_stay_aggregate():
    """Caller-supplied peaks are a MEASURED system aggregate: the mesh
    multiplier must not double-scale them."""
    from deepspeed_tpu.parallel import mesh as mesh_mod
    from deepspeed_tpu.telemetry.costs import ProgramCostModel

    mesh_mod.set_mesh(mesh_mod.initialize_mesh(data=8))
    try:
        m = ProgramCostModel(peak_flops=123.0, peak_bytes_per_s=45.0)
        assert m.peak_flops == 123.0
        assert m.peak_bytes_per_s == 45.0
        assert m.num_devices == 8  # recorded for attribution regardless
    finally:
        mesh_mod.reset_mesh()
