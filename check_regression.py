#!/usr/bin/env python
"""Gate on benchmark regressions between two ``bench.py --json`` files.

Usage:
    python check_regression.py BASELINE.json CANDIDATE.json \
        [--metric PATH[:higher|lower]] ... [--threshold 0.10] \
        [--max-recompiles N] [--min-goodput FRAC] \
        [--max-overhead-pct X] [--warn-metric PATH[:higher|lower]] ...

Each ``--metric`` names a dotted path into the result object (e.g.
``value``, ``detail.stall_free.requests_per_s``) with an optional
direction suffix: ``higher`` (default) means larger is better,
``lower`` means smaller is better. With no ``--metric``, the headline
``value:higher`` is checked.

A metric regresses when the candidate is worse than the baseline by
more than ``--threshold`` (default 0.10 = 10%), measured relative to
the baseline. Improvements and within-threshold noise pass.

``--max-recompiles N`` additionally gates on compilation churn: the
candidate's ``detail.recompiles_after_warmup`` (every serving
``bench.py`` row reports it from the runtime recompile watchdog) must
not exceed N. This is an absolute cap on the candidate alone — no
baseline comparison and no threshold slack, because post-warmup
recompiles are a zero-tolerance invariant, not a noisy measurement.

The ``paging`` row gates through the same machinery — e.g.::

    python check_regression.py BENCH_paging.base.json BENCH_paging.json \
        --metric value:higher \
        --metric detail.prefill_hit_ms:lower \
        --metric detail.prefix_hit_rate:higher \
        --max-recompiles 0

``value`` is peak resident requests at equal KV HBM (paged over
contiguous; the PR-7 acceptance floor is 1.5), ``prefill_hit_ms`` is
the admit-to-first-token latency a prefix hit pays, and the recompile
cap holds across page churn, prefix hits, and copy-on-write forks.

``--require-zero-leaks`` gates the fault-tolerance invariants the
``serving-chaos`` row reports: the candidate's ``detail.slot_leaks``
must be exactly 0 and ``detail.invariants_ok`` /
``detail.timelines_complete`` must both be true. Like
``--max-recompiles``, these are absolute zero-tolerance checks on the
candidate alone — a leaked slot under fault injection is a bug, not a
regression to be thresholded.

``--require-complete-journeys`` gates the fleet observability
invariant the ``serving-disagg`` row reports: the candidate's
``detail.journeys.complete`` must equal ``detail.journeys.finished``
— every cross-replica request journey that reached a terminal hop
(finish/reject/cancel/failed) must stitch COMPLETE: every home's
timeline closed and no request parked mid-handoff. Absolute on the
candidate alone; a missing or non-numeric ``detail.journeys`` block is
a usage error (exit 2), so a bench that silently stopped emitting the
block can never pass::

    python check_regression.py BENCH_serving_disagg.base.json \
        BENCH_serving_disagg.json \
        --max-overhead-pct 3 --require-complete-journeys \
        --max-recompiles 0

``--min-goodput FRAC`` and ``--max-overhead-pct X`` gate the
``efficiency`` detail block the serving-stall and paging rows report
from the runtime cost model + SLO tracker: the candidate's
``detail.efficiency.goodput_slo`` (finished-within-SLO over admitted)
must be >= FRAC, and ``detail.efficiency.overhead_pct`` (telemetry
instrumentation time over accumulated step wall) must be <= X. Both
are absolute caps on the candidate alone, like ``--max-recompiles`` —
an unobservable server and a heavyweight observer are defects, not
noise.

The ``serving-async`` row combines the three absolute gates — its
``detail.efficiency.goodput_slo`` is the TOP priority class's goodput,
measured through the real HTTP/SSE front end while the bottom class is
actively shed by burn-rate control::

    python check_regression.py BENCH_serving_async.base.json \
        BENCH_serving_async.json \
        --min-goodput 0.95 --require-zero-leaks --max-recompiles 0

``--max-lint-errors N`` gates on static trace-safety debt: it reads a
``bin/graftlint --json`` report named by ``--lint-json FILE`` and
requires ``summary.errors`` (unsuppressed, unbaselined graftlint
errors) to be at most N — the serving gate runs with N=0.  Like
``--max-recompiles`` this is an absolute cap on the candidate alone: a
static invariant violation is a defect, not a regression to be
thresholded.  ``--max-lint-errors`` without ``--lint-json`` is a usage
error (exit 2).  ``--lint-json`` repeats: the serving gate passes one
all-tiers report plus a graftown (``--tier own``) ownership report over
serving/, so a lifecycle finding and a trace-safety finding gate
identically::

    bin/graftlint deepspeed_tpu/serving deepspeed_tpu/telemetry \
        --json > LINT.json
    bin/graftlint --tier own deepspeed_tpu/serving --json > OWN.json
    python check_regression.py BASE.json CAND.json \
        --lint-json LINT.json --lint-json OWN.json --max-lint-errors 0

``--require-signature-match`` gates the zero-recompile invariant
STATICALLY: it reads the ``signatures.json`` warmup manifest named by
``--signatures-json FILE`` (exported by ``bench.py --signatures`` on
the serving-stall and paging rows), re-enumerates the reachable
abstract-signature set with graftcheck's interpreter under the
manifest's recorded configs (stdlib ast only — no jax import), and
fails on ANY divergence in either direction: a signature the warmup
never traced will compile post-warmup; a runtime signature the static
enumeration missed means the checker lost coverage. Like
``--max-recompiles`` this is absolute on the candidate alone, and
``--require-signature-match`` without ``--signatures-json`` is a usage
error (exit 2)::

    python bench.py serving-stall --json BENCH.json \
        --signatures signatures.json
    python check_regression.py BASE.json BENCH.json \
        --signatures-json signatures.json --require-signature-match

The ``serving-decode`` row composes the full stack — a hard gate on
the kernel arm's p99 inter-token gap, a warn-only MFU floor, and both
zero-recompile gates (runtime watchdog + static signature match)::

    python bench.py serving-decode --json BENCH_serving_decode.json \
        --signatures signatures.json
    python check_regression.py BENCH_serving_decode.base.json \
        BENCH_serving_decode.json \
        --metric value:lower \
        --warn-metric detail.efficiency.mfu:higher \
        --max-recompiles 0 \
        --signatures-json signatures.json --require-signature-match

``--warn-metric PATH[:higher|lower]`` runs the same relative
comparison as ``--metric`` but never fails the gate — it prints
``WARNING`` instead of ``REGRESSION``. Use it for metrics that are
informative but machine-dependent, e.g. ``detail.efficiency.mfu`` on a
CPU validation box, where XLA's cost model and the nominal peak-FLOPS
denominator make the absolute value meaningless but a large swing is
still worth a look.

Exit codes: 0 = all metrics within threshold, 1 = at least one
regression, 2 = unusable input (missing file, bad JSON, missing metric,
non-numeric value). The driver treats 1 as "block the PR" and 2 as
"fix the invocation", so a typo'd metric name can never pass silently.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Tuple


def _load_analysis():
    """Import ``deepspeed_tpu.analysis`` standalone (stdlib ast only,
    same trick as ``bin/graftlint``) so the signature gate never pays —
    or depends on — the heavyweight jax import."""
    import importlib.util

    name = "_graftlint_analysis"
    if name in sys.modules:
        return sys.modules[name]
    pkg = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "deepspeed_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg, "__init__.py"),
        submodule_search_locations=[pkg])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load(path: str) -> Any:
    try:
        with open(path) as f:
            return json.load(f)
    except OSError as e:
        print(f"check_regression: cannot read {path}: {e}", file=sys.stderr)
        sys.exit(2)
    except ValueError as e:
        print(f"check_regression: {path} is not valid JSON: {e}",
              file=sys.stderr)
        sys.exit(2)


def _walk(obj: Any, dotted: str, path: str) -> Any:
    cur = obj
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            print(f"check_regression: metric '{dotted}' not found in "
                  f"{path} (missing key '{part}')", file=sys.stderr)
            sys.exit(2)
        cur = cur[part]
    return cur


def _resolve(obj: Any, dotted: str, path: str) -> float:
    cur = _walk(obj, dotted, path)
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        print(f"check_regression: metric '{dotted}' in {path} is not a "
              f"number: {cur!r}", file=sys.stderr)
        sys.exit(2)
    return float(cur)


def _parse_metric(spec: str) -> Tuple[str, str]:
    dotted, sep, direction = spec.partition(":")
    if not sep:
        return dotted, "higher"
    if direction not in ("higher", "lower"):
        print(f"check_regression: bad direction '{direction}' in "
              f"'{spec}' (use 'higher' or 'lower')", file=sys.stderr)
        sys.exit(2)
    return dotted, direction


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Compare two bench.py --json files; exit 1 on "
                    "regression beyond threshold.")
    ap.add_argument("baseline")
    ap.add_argument("candidate")
    ap.add_argument("--metric", action="append", default=[],
                    metavar="PATH[:higher|lower]",
                    help="dotted path into the JSON (repeatable); "
                         "default: value:higher")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="max allowed relative regression (default 0.10)")
    ap.add_argument("--max-recompiles", type=int, default=None,
                    metavar="N",
                    help="absolute cap on the candidate's "
                         "detail.recompiles_after_warmup (no baseline, "
                         "no threshold slack)")
    ap.add_argument("--min-goodput", type=float, default=None,
                    metavar="FRAC",
                    help="absolute floor on the candidate's "
                         "detail.efficiency.goodput_slo (no baseline, "
                         "no threshold slack)")
    ap.add_argument("--max-overhead-pct", type=float, default=None,
                    metavar="X",
                    help="absolute cap on the candidate's "
                         "detail.efficiency.overhead_pct — telemetry "
                         "instrumentation time over step wall")
    ap.add_argument("--warn-metric", action="append", default=[],
                    metavar="PATH[:higher|lower]",
                    help="like --metric but warn-only: prints WARNING "
                         "on a beyond-threshold move, never exits 1 "
                         "(for machine-dependent metrics like "
                         "detail.efficiency.mfu on CPU)")
    ap.add_argument("--lint-json", metavar="FILE", action="append",
                    default=None,
                    help="a `bin/graftlint --json` report to gate with "
                         "--max-lint-errors; repeatable, so one run can "
                         "gate e.g. a lint-tier and a `--tier sync` "
                         "report together (the cap applies to each "
                         "report independently)")
    ap.add_argument("--max-lint-errors", type=int, default=None,
                    metavar="N",
                    help="absolute cap on summary.errors in each "
                         "--lint-json report (unsuppressed graftlint "
                         "errors; the serving gate uses 0)")
    ap.add_argument("--signatures-json", metavar="FILE", default=None,
                    help="a signatures.json warmup manifest (from "
                         "`bench.py --signatures`) to gate with "
                         "--require-signature-match")
    ap.add_argument("--require-signature-match", action="store_true",
                    help="absolute gate: graftcheck's statically "
                         "enumerated signature set must equal the "
                         "--signatures-json runtime warmup manifest in "
                         "both directions (no jax import)")
    ap.add_argument("--require-complete-journeys", action="store_true",
                    help="absolute gate on the candidate's fleet "
                         "journey completeness (serving-disagg row): "
                         "detail.journeys.complete == "
                         "detail.journeys.finished — every journey that "
                         "reached a terminal hop must stitch with all "
                         "homes closed and nothing parked")
    ap.add_argument("--require-zero-leaks", action="store_true",
                    help="absolute gate on the candidate's fault-"
                         "tolerance invariants (serving-chaos row): "
                         "detail.slot_leaks == 0 and "
                         "detail.invariants_ok / "
                         "detail.timelines_complete true")
    args = ap.parse_args(argv)

    base = _load(args.baseline)
    cand = _load(args.candidate)
    specs = args.metric or ["value:higher"]

    if args.max_lint_errors is not None and args.lint_json is None:
        print("check_regression: --max-lint-errors requires --lint-json "
              "FILE (a `bin/graftlint --json` report)", file=sys.stderr)
        sys.exit(2)
    if args.require_signature_match and args.signatures_json is None:
        print("check_regression: --require-signature-match requires "
              "--signatures-json FILE (a `bench.py --signatures` warmup "
              "manifest)", file=sys.stderr)
        sys.exit(2)

    failed = False
    if args.require_signature_match:
        man = _load(args.signatures_json)
        progs = man.get("programs") if isinstance(man, dict) else None
        if not isinstance(progs, dict):
            print(f"check_regression: {args.signatures_json} is not a "
                  "signatures.json manifest (missing 'programs')",
                  file=sys.stderr)
            sys.exit(2)
        analysis = _load_analysis()
        envs = man.get("configs") or analysis.default_check_envs()
        res = analysis.enumerate_union(
            envs, os.path.dirname(os.path.abspath(__file__)))
        static = {k: sorted(v) for k, v in res.programs.items()}
        diffs = [f"{f.path}:{f.line}: {f.rule}: {f.message}"
                 for f in res.findings]
        diffs += analysis.diff_manifest(static, progs)
        worse = bool(diffs)
        tag = "REGRESSION" if worse else "ok"
        print(f"{tag:>10}  signatures [graftcheck] (absolute): "
              f"{len(diffs)} divergence(s) vs {args.signatures_json}")
        for d in diffs:
            print(f"            {d}")
        failed |= worse
    if args.max_lint_errors is not None:
        for lint_path in args.lint_json:
            lint = _load(lint_path)
            e = _resolve(lint, "summary.errors", lint_path)
            worse = e > args.max_lint_errors
            tag = "REGRESSION" if worse else "ok"
            print(f"{tag:>10}  summary.errors [graftlint] (absolute): "
                  f"candidate={e:g} max={args.max_lint_errors} "
                  f"({os.path.basename(lint_path)})")
            failed |= worse
    if args.require_zero_leaks:
        leaks = _resolve(cand, "detail.slot_leaks", args.candidate)
        worse = leaks != 0
        print(f"{'REGRESSION' if worse else 'ok':>10}  detail.slot_leaks "
              f"(absolute): candidate={leaks:g} required=0")
        failed |= worse
        for dotted in ("detail.invariants_ok", "detail.timelines_complete"):
            val = _walk(cand, dotted, args.candidate)
            if not isinstance(val, bool):
                print(f"check_regression: metric '{dotted}' in "
                      f"{args.candidate} is not a boolean: {val!r}",
                      file=sys.stderr)
                sys.exit(2)
            print(f"{'ok' if val else 'REGRESSION':>10}  {dotted} "
                  f"(absolute): candidate={val} required=True")
            failed |= not val
    if args.require_complete_journeys:
        fin = _resolve(cand, "detail.journeys.finished", args.candidate)
        comp = _resolve(cand, "detail.journeys.complete", args.candidate)
        worse = comp != fin
        tag = "REGRESSION" if worse else "ok"
        print(f"{tag:>10}  detail.journeys (absolute): "
              f"complete={comp:g} finished={fin:g} required=equal")
        failed |= worse
    if args.max_recompiles is not None:
        dotted = "detail.recompiles_after_warmup"
        r = _resolve(cand, dotted, args.candidate)
        worse = r > args.max_recompiles
        tag = "REGRESSION" if worse else "ok"
        print(f"{tag:>10}  {dotted} (absolute): candidate={r:g} "
              f"max={args.max_recompiles}")
        failed |= worse
    if args.min_goodput is not None:
        dotted = "detail.efficiency.goodput_slo"
        g = _resolve(cand, dotted, args.candidate)
        worse = g < args.min_goodput
        tag = "REGRESSION" if worse else "ok"
        print(f"{tag:>10}  {dotted} (absolute): candidate={g:g} "
              f"min={args.min_goodput:g}")
        failed |= worse
    if args.max_overhead_pct is not None:
        dotted = "detail.efficiency.overhead_pct"
        o = _resolve(cand, dotted, args.candidate)
        worse = o > args.max_overhead_pct
        tag = "REGRESSION" if worse else "ok"
        print(f"{tag:>10}  {dotted} (absolute): candidate={o:g} "
              f"max={args.max_overhead_pct:g}")
        failed |= worse
    for spec in args.warn_metric:
        dotted, direction = _parse_metric(spec)
        b = _resolve(base, dotted, args.baseline)
        c = _resolve(cand, dotted, args.candidate)
        if b == 0:
            delta = 0.0 if c == 0 else (1.0 if c > 0 else -1.0)
        else:
            delta = (c - b) / abs(b)
        moved = delta < -args.threshold if direction == "higher" \
            else delta > args.threshold
        tag = "WARNING" if moved else "ok"
        print(f"{tag:>10}  {dotted} ({direction}, warn-only): "
              f"baseline={b:g} candidate={c:g} delta={delta:+.1%}")
    for spec in specs:
        dotted, direction = _parse_metric(spec)
        b = _resolve(base, dotted, args.baseline)
        c = _resolve(cand, dotted, args.candidate)
        if b == 0:
            # no meaningful relative delta; only direction flips count
            delta = 0.0 if c == 0 else (1.0 if c > 0 else -1.0)
        else:
            delta = (c - b) / abs(b)
        worse = delta < -args.threshold if direction == "higher" \
            else delta > args.threshold
        tag = "REGRESSION" if worse else "ok"
        print(f"{tag:>10}  {dotted} ({direction}): "
              f"baseline={b:g} candidate={c:g} delta={delta:+.1%}")
        failed |= worse
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
