"""ZeRO-Inference at larger-than-HBM scale on the real chip.

The single-chip analog of the reference's BLOOM-176B ZeRO-Inference
headline (docs/_posts/2022-09-10-zero-inference.md:21): a model several
times the device's HBM lives host-resident and streams through the chip
one transformer layer at a time via :class:`ZeroInferenceEngine`.
Records scoring throughput (tokens/s) and the effective host→device
streaming bandwidth, which on this harness is bounded by the axon tunnel
(~0.3 GB/s measured), not PCIe/DMA — noted in BASELINE.md.

Weights are random (the throughput claim doesn't depend on their values);
every layer gets its own physical buffer (no broadcast aliasing — the
host-RAM footprint and per-layer transfers are real), filled from one
random template to keep setup O(minutes).

Usage: python benchmarks/zero_inference_bench.py --params-b 32
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def build_host_params(model, cfg, ids, std=0.01):
    """Full host-resident bf16 param tree from a single random template
    layer (shapes via eval_shape — nothing big ever touches the device)."""
    import ml_dtypes

    bf16 = ml_dtypes.bfloat16
    shapes = jax.eval_shape(
        lambda r: model.init({"params": r}, ids, method=model.logits),
        jax.random.PRNGKey(0))["params"]
    rng = np.random.default_rng(0)

    def fill(path, sd):
        shape = sd.shape
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        if len(shape) >= 1 and shape[0] == cfg.n_layer and "blocks" in name:
            # scan-stacked: one random template layer, copied to every slice
            template = rng.standard_normal(shape[1:], np.float32)
            template = (template * std).astype(bf16) if "kernel" in name or \
                "embedding" in name else (
                np.ones(shape[1:], bf16) if name.endswith("scale")
                else np.zeros(shape[1:], bf16))
            out = np.empty(shape, bf16)
            # uint16-view copy: a raw memcpy per slice (the ml_dtypes bf16
            # assignment path is orders of magnitude slower at 10s of GB)
            out.view(np.uint16)[:] = template.view(np.uint16)
            return out
        if name.endswith("scale"):
            return np.ones(shape, bf16)
        if name.endswith("bias"):
            return np.zeros(shape, bf16)
        return (rng.standard_normal(shape, np.float32) * std).astype(bf16)

    return jax.tree_util.tree_map_with_path(fill, shapes)


def start_heartbeat():
    """Keep-alive transfers: the tunneled host->device link cold-starts
    after idle gaps (a 5 s pause costs ~30 s on the next stream). Returns
    the Event that stops the thread."""
    import threading

    stop_beat = threading.Event()
    beat_buf = np.ones(64 * 1024, np.int8)

    def _heartbeat():
        while not stop_beat.is_set():
            jax.device_put(beat_buf).block_until_ready()
            stop_beat.wait(0.05)

    threading.Thread(target=_heartbeat, daemon=True).start()
    return stop_beat


def compare_int8(cfg, host, ids, n_params):
    """A/B/A: bf16 stream, int8 stream, bf16 again (order effects on the
    tunneled link are real); one instrumented pass each, readbacks last.

    RSS budget (pathology #1: staged bytes are retained per pass): params
    + 0.5x int8 copy + 2x bf16 passes + 1x int8 pass ≈ 4.5x the bf16
    model bytes — size --params-b so that fits host RAM (≤4B here)."""
    from deepspeed_tpu.inference.zero_inference import ZeroInferenceEngine

    stop_beat = start_heartbeat()

    engines = {
        "bf16": ZeroInferenceEngine(cfg, host, prefetch=1),
        "int8": ZeroInferenceEngine(cfg, host, prefetch=1, int8=True),
    }
    rows = {}
    logits = {}
    wire_bytes = {}
    for name in ("bf16", "int8", "bf16_again"):
        eng = engines[name.split("_")[0]]
        times = []
        t0 = time.perf_counter()
        logits[name] = eng.forward(ids, layer_times=times)
        logits[name].block_until_ready()
        wire = sum(eng._leaf_nbytes) * eng.n_layer
        wire_bytes[name] = wire
        best = sorted(times[1:])[:max(1, (len(times) - 1) // 2)]
        rows[name] = {
            "pass_s": round(time.perf_counter() - t0, 2),
            "wire_gb": round(wire / 1e9, 2),
            "layer_times_s": [round(t, 3) for t in times],
            "best_half_layers_gbps": round(
                (wire / eng.n_layer) * len(best) / sum(best) / 1e9, 3),
        }
        print(name, rows[name]["pass_s"], "s,", rows[name]["wire_gb"],
              "GB wire", flush=True)
    stop_beat.set()
    ll = {n: engines[n.split("_")[0]].score_logits(logits[n], ids)
          for n in logits}
    agree = float(np.mean(np.asarray(logits["bf16"], np.float32).argmax(-1) ==
                          np.asarray(logits["int8"], np.float32).argmax(-1)))
    result = {
        "kind": "int8_stream_compare",
        "params_b": n_params / 1e9,
        "rows": rows,
        "argmax_agreement": agree,
        "mean_loglik": {n: float(np.mean(v)) for n, v in ll.items()},
        "wire_ratio": wire_bytes["int8"] / wire_bytes["bf16"],
        "backend": jax.default_backend(),
    }
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "int8_stream_results.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--params-b", type=float, default=32.0,
                    help="target model size in billions of parameters")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--compare-int8", action="store_true",
                    help="A/B/A: bf16 stream vs int8-at-rest stream "
                         "(half the wire bytes) on the same model")
    args = ap.parse_args()

    from deepspeed_tpu.inference.zero_inference import ZeroInferenceEngine
    from deepspeed_tpu.models.transformer_lm import (
        TransformerLM,
        transformer_config,
    )

    # size the model: params ≈ 12 * L * d^2 (+ embed); fix d, solve L
    d = 6656 if args.params_b >= 8 else 2048
    L = max(2, round(args.params_b * 1e9 / (12 * d * d)))
    cfg = transformer_config(
        "gpt2", vocab_size=32000, n_embd=d, n_layer=L,
        n_head=d // args.head_dim, max_seq_len=args.seq,
        decode_kernel="off")
    model = TransformerLM(cfg)
    ids = jnp.asarray(np.random.default_rng(1).integers(
        0, 32000, (args.batch, args.seq)), jnp.int32)

    t0 = time.perf_counter()
    host = build_host_params(model, cfg, ids[:1, :8])
    total_bytes = sum(np.asarray(l).nbytes
                      for l in jax.tree_util.tree_leaves(host))
    n_params = sum(np.asarray(l).size
                   for l in jax.tree_util.tree_leaves(host))
    print(f"built {n_params/1e9:.2f}B params ({total_bytes/1e9:.1f} GB "
          f"host-resident) in {time.perf_counter()-t0:.0f}s", flush=True)

    if args.compare_int8:
        return compare_int8(cfg, host, ids, n_params)

    engine = ZeroInferenceEngine(cfg, host, dtype=jnp.bfloat16, prefetch=1)
    stream_bytes = sum(np.asarray(l).nbytes for l in
                       jax.tree_util.tree_leaves(host["blocks"]["block"]))

    stop_beat = start_heartbeat()

    # Two axon-tunnel pathologies constrain the measurement protocol
    # (both absent on directly-attached TPUs):
    #   1. every H2D transfer permanently retains its staged bytes in host
    #      RSS, so a process affords ONE larger-than-RAM/2 streaming pass;
    #   2. any D2H readback degrades subsequent H2D ~50x process-wide.
    # Protocol: a single forward pass, instrumented per layer; the block
    # jit compiles during layer 0, so the sustained streaming rate is
    # taken over the remaining layers. Numeric validation (score with its
    # readback) runs last.
    layer_s = []
    t_pass = time.perf_counter()
    logits = engine.forward(ids, layer_times=layer_s)
    logits.block_until_ready()
    dt = time.perf_counter() - t_pass
    for i in range(0, len(layer_s), 8):
        print(f"layer {i}: {layer_s[i]:.2f}s", flush=True)
    per_layer_bytes = stream_bytes / engine.n_layer
    best_half = sorted(layer_s[1:])[:max(1, (engine.n_layer - 1) // 2)]
    best_half_gbps = per_layer_bytes * len(best_half) / sum(best_half) / 1e9
    warm_s = layer_s[0]

    # numeric validation from the logits already on device (a second
    # score() pass would re-stream the model and OOM on pathology #1);
    # the readback happens here, after all measurements
    t0 = time.perf_counter()
    ll = engine.score_logits(logits, ids)
    score_s = time.perf_counter() - t0
    stop_beat.set()
    assert np.all(np.isfinite(ll)), "non-finite scores"
    tokens = args.batch * args.seq
    result = {
        "params_b": n_params / 1e9,
        "model_gb": total_bytes / 1e9,
        "hbm_gb": 16.0,
        "model_x_hbm": total_bytes / 16e9,
        "batch": args.batch, "seq": args.seq,
        "layers": L, "d_model": d,
        "score_tokens_per_s": tokens / dt,
        "elapsed_s": dt,
        "layer_times_s": [round(t, 2) for t in layer_s],
        "compile_layer0_s": round(warm_s, 1),
        "best_half_layers_gbps": round(best_half_gbps, 3),
        "score_with_readback_s": round(score_s, 1),
        "stream_gb_per_pass": stream_bytes / 1e9,
        "effective_host_to_device_gbps": stream_bytes / dt / 1e9,
        "mean_loglik": float(np.mean(ll)),
        "backend": jax.default_backend(),
    }
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "zero_inference_results.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
