"""Block-sparse attention benchmark on the real chip (VERDICT r2 next #2).

Per sequence length, times training fwd+bwd for:
  - dense XLA fused attention (causal)
  - dense Pallas flash attention
  - gather-formulation block-sparse (jnp)
  - fused Pallas block-sparse (splash-style)

using a Fixed unidirectional sparsity config at the TPU-native granule
(block 512 — the MXU-efficient flash-tile size; the reference's Triton
granule is 16) with a 2k-token local window + Fixed-pattern globals — the
analog of the reference's block-16 Triton benchmarks
(docs/_posts/2020-09-09-sparse-attention.md: up to 6.3x faster BERT
pretraining). Writes ``benchmarks/sparse_attn_bench_results.json``.
Run WITHOUT a platform override (claims the real TPU through the tunnel).
"""

from __future__ import annotations

import json
import os

from attn_bench import timed


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.ops.attention.flash_attention import flash_attention
    from deepspeed_tpu.ops.sparse_attention.pallas_kernel import (
        block_sparse_flash_attention,
        layout_to_schedule,
    )
    from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
        block_sparse_attention,
    )
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
        FixedSparsityConfig,
    )
    import math

    print("backend:", jax.default_backend(), jax.devices())
    H, D, BLOCK = 12, 64, 512  # TPU-native granule: the flash-tile size (128 = Triton-analog minimum, but MXU efficiency wants 512)
    rng = np.random.default_rng(0)
    results = []

    def xla_attn(q, k, v):
        s = jnp.einsum("bthd,bshd->bhts", q, k) / math.sqrt(D)
        mask = jnp.tril(jnp.ones((q.shape[1], q.shape[1]), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
        return jnp.einsum("bhts,bshd->bthd", p, v)

    def loss_of(attn):
        def f(q, k, v):
            return attn(q, k, v).astype(jnp.float32).sum()

        grad_f = jax.grad(f, argnums=(0, 1, 2))

        def scalar(q, k, v):
            gq, gk, gv = grad_f(q, k, v)
            return (gq.astype(jnp.float32).sum() +
                    gk.astype(jnp.float32).sum() +
                    gv.astype(jnp.float32).sum())

        return scalar

    for seq in (4096, 8192, 16384, 32768):
        B = max(1, 8192 // seq)
        cfg = FixedSparsityConfig(num_heads=H, block=BLOCK,
                                  num_local_blocks=4, num_global_blocks=1,
                                  attention="unidirectional")
        layout = cfg.make_layout(seq)
        _, cnt = layout_to_schedule(layout)
        density = float(layout.sum()) / layout[0].size / H
        shape = (B, seq, H, D)
        q, k, v = (jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
                   for _ in range(3))
        row = {"kind": "sparse_train_fwd_bwd", "seq": seq, "batch": B,
               "heads": H, "head_dim": D, "block": BLOCK,
               "layout_density": round(density, 4),
               "max_live_blocks": int(cnt.max())}

        candidates = [
            ("xla_dense", xla_attn),
            ("flash_dense", lambda q, k, v: flash_attention(q, k, v,
                                                            causal=True)),
            ("gather_sparse", lambda q, k, v: block_sparse_attention(
                q, k, v, layout, BLOCK, causal=True)),
            ("pallas_sparse", lambda q, k, v: block_sparse_flash_attention(
                q, k, v, layout, BLOCK, causal=True)),
        ]
        for name, attn in candidates:
            try:
                dt = timed(loss_of(attn), q, k, v, iters=10)
                row[f"{name}_ms"] = round(dt * 1e3, 3)
            except Exception as e:  # OOM for dense paths at long seq
                row[f"{name}_ms"] = None
                row[f"{name}_error"] = str(e)[:160]
        if row.get("xla_dense_ms") and row.get("pallas_sparse_ms"):
            row["vs_xla_dense"] = round(
                row["xla_dense_ms"] / row["pallas_sparse_ms"], 2)
        if row.get("gather_sparse_ms") and row.get("pallas_sparse_ms"):
            row["vs_gather"] = round(
                row["gather_sparse_ms"] / row["pallas_sparse_ms"], 2)
        if row.get("flash_dense_ms") and row.get("pallas_sparse_ms"):
            row["vs_flash_dense"] = round(
                row["flash_dense_ms"] / row["pallas_sparse_ms"], 2)
        results.append(row)
        print(row)

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "sparse_attn_bench_results.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
    print("wrote", out)


if __name__ == "__main__":
    main()
