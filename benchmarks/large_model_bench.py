"""GPT-2 Large-class (774M) single-chip training row — measured.

The flagship row (bench.py) is 350M; this is the same protocol one size
up, answering "does the MFU hold when the model 2.2x's?". Earlier
round-5 attempts at this size died in remote-compile with HTTP 500 —
root-caused this session to a compile-time HBM OOM (dots-remat at
mbs4 wants 18.4 GB; ZeRO-2 single-chip optimizer state for 774M is
~10.9 GB), not infra: full remat at mbs2 x gas32 fits with room.

Run ON the real chip: python benchmarks/large_model_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _bench_util import enable_persistent_cache  # noqa: E402

V5E_PEAK_TFLOPS = 197.0
SEQ = 1024


def run_config(mbs, gas, remat_policy):
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config(vocab_size=50257, n_positions=SEQ, n_embd=1280,
                     n_layer=36, n_head=20, dtype=jnp.bfloat16,
                     remat=True, remat_policy=remat_policy)
    engine, _, _, _ = ds.initialize(model=GPT2LMHeadModel(cfg), config={
        "train_micro_batch_size_per_gpu": mbs,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam",
                      "params": {"lr": 3e-4, "weight_decay": 0.1}},
        "steps_per_print": 1000000,
    })
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size,
        (engine.train_batch_size(), SEQ)).astype(np.int32)}
    for _ in range(2):  # compile + settle
        loss = engine.train_batch(batch=batch)
    jax.block_until_ready(loss)
    steps = 5
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / steps
    tok_s = engine.train_batch_size() * SEQ / dt
    n = engine.num_parameters
    tf6 = tok_s * 6 * n / 1e12
    return {
        "config": f"mbs{mbs}xgas{gas} remat={remat_policy}",
        "params_m": round(n / 1e6, 1),
        "tokens_per_s_chip": round(tok_s, 1),
        "tflops_6n": round(tf6, 2),
        "mfu_pct_6n": round(100 * tf6 / V5E_PEAK_TFLOPS, 1),
        "loss": round(float(loss), 4),
    }


def main():
    enable_persistent_cache()
    out_path = os.path.join(os.path.dirname(__file__),
                            "large_model_results.json")
    result = {"model": "GPT-2 Large-class 774M (36L x 1280 x 20h, seq 1024)",
              "note": "dots remat OOMs at this size on one chip "
                      "(compile-time 18.4G at mbs4 / 16.3G at mbs2 vs "
                      "15.75G HBM); full remat trades recompute for fit. "
                      "Sweep (fresh process each): mbs2xgas32 40.0-40.4%, "
                      "mbs4xgas16 38.0%, mbs6 OOM — this script measures "
                      "the winner; one engine per process (a second "
                      "engine OOMs against the first's live buffers)",
              "rows": []}
    row = run_config(2, 32, "full")
    result["rows"].append(row)
    print(f"[large_model] {row}", flush=True)
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(f"[large_model] -> {out_path}", flush=True)


if __name__ == "__main__":
    main()
