"""Block-sparse attention at BigBird-realistic density + model-level row
(VERDICT r3 #3).

Two measurements the round-3 microbench did not make:

1. **Kernel rows at density <= 0.16** — the regime block-sparsity exists
   for. Round 3 benchmarked 0.28-0.375, where a causal dense flash kernel
   (effective density 0.5) does a comparable amount of work and the sparse
   kernel's scheduling overhead erased the FLOP savings (0.92-1.31x).
   BigBird-style layouts (sliding window + random + global) at 5-8%
   density carry a 4-6x FLOP advantage over causal flash — the honest
   comparator, this repo's own best dense path.

2. **Model-level training row** — GPT-2 at seq 8k/16k, tokens/s with the
   model's attention routed through the sparse kernel
   (``GPT2Config.sparse_attention``) vs the flash-dense model: the
   repo-native analog of the reference's "up to 6.1x faster GPT-2
   pretraining" claim (docs/_posts/2020-09-09-sparse-attention.md:31).

Writes ``benchmarks/sparse_lowdensity_results.json``. Run ON the chip.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _bench_util import enable_persistent_cache  # noqa: E402
from attn_bench import timed  # noqa: E402


def kernel_rows():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.ops.attention.flash_attention import flash_attention
    from deepspeed_tpu.ops.sparse_attention.pallas_kernel import (
        block_sparse_flash_attention,
        layout_to_schedule,
    )
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
        BigBirdSparsityConfig,
    )

    H, D = 12, 64
    rng = np.random.default_rng(0)
    rows = []

    def loss_of(attn):
        def f(q, k, v):
            return attn(q, k, v).astype(jnp.float32).sum()

        grad_f = jax.grad(f, argnums=(0, 1, 2))

        def scalar(q, k, v):
            gq, gk, gv = grad_f(q, k, v)
            return (gq.astype(jnp.float32).sum() +
                    gk.astype(jnp.float32).sum() +
                    gv.astype(jnp.float32).sum())

        return scalar

    CASES = [
        # (seq, block, window, random, global)
        (8192, 256, 3, 1, 1),     # d ~ 0.15
        (8192, 512, 3, 1, 1),     # d ~ 0.29 (granule-bound floor at 8k)
        (16384, 512, 3, 1, 1),    # d ~ 0.15
        (16384, 256, 3, 1, 1),    # d ~ 0.08
        (32768, 512, 3, 1, 1),    # d ~ 0.08
    ]
    for seq, block, w, r, g in CASES:
        B = max(1, 8192 // seq)
        cfg = BigBirdSparsityConfig(
            num_heads=H, block=block, num_random_blocks=r,
            num_sliding_window_blocks=w, num_global_blocks=g,
            attention="unidirectional")
        layout = cfg.make_layout(seq)
        _, cnt = layout_to_schedule(layout)
        density = float(layout.sum()) / layout[0].size / H
        shape = (B, seq, H, D)
        q, k, v = (jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
                   for _ in range(3))
        row = {"kind": "bigbird_lowdensity_fwd_bwd", "seq": seq,
               "batch": B, "block": block,
               "pattern": f"w{w}r{r}g{g}",
               "layout_density": round(density, 4),
               "max_live_blocks": int(cnt.max())}
        for name, attn in [
            ("flash_dense", lambda q, k, v: flash_attention(
                q, k, v, causal=True)),
            ("pallas_sparse", lambda q, k, v: block_sparse_flash_attention(
                q, k, v, layout, block, causal=True)),
        ]:
            try:
                dt = timed(loss_of(attn), q, k, v, iters=10)
                row[f"{name}_ms"] = round(dt * 1e3, 3)
            except Exception as e:
                row[f"{name}_ms"] = None
                row[f"{name}_error"] = str(e)[:160]
        if row.get("flash_dense_ms") and row.get("pallas_sparse_ms"):
            row["vs_flash_dense"] = round(
                row["flash_dense_ms"] / row["pallas_sparse_ms"], 2)
            # FLOP advantage the layout carries over causal dense
            row["flop_advantage"] = round(0.5 / density, 2)
        rows.append(row)
        print("[sparse_ld]", row, flush=True)
    return rows


def model_rows(seq=8192, block=512):
    """GPT-2 training tokens/s: sparse-attention model vs flash-dense.
    block 512 is the measured-efficient granule (the 256 granule wastes
    the MXU — kernel rows)."""
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
        BigBirdSparsityConfig,
    )

    B = 1
    rows = []
    variants = {
        "flash_dense": dict(use_flash_attention=True),
        "bigbird_sparse": dict(sparse_attention=BigBirdSparsityConfig(
            num_heads=12, block=block, num_random_blocks=1,
            num_sliding_window_blocks=3, num_global_blocks=1,
            attention="unidirectional")),
    }
    for name, extra in variants.items():
        cfg = GPT2Config(n_positions=seq, n_embd=768, n_layer=12, n_head=12,
                         remat=True, **extra)
        engine, _, _, _ = ds.initialize(
            model=GPT2LMHeadModel(cfg),
            config={"train_micro_batch_size_per_gpu": B,
                    "gradient_accumulation_steps": 1,
                    "zero_optimization": {"stage": 0},
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                    "bf16": {"enabled": True}, "steps_per_print": 10 ** 9})
        rng = np.random.default_rng(0)
        walls = []
        for i in range(8):
            b = {"input_ids": rng.integers(
                0, 50257, (B, seq)).astype(np.int32)}
            t0 = time.perf_counter()
            loss = engine.train_batch(batch=b)
            jax.block_until_ready(loss)
            walls.append(time.perf_counter() - t0)
        med = float(np.median(walls[3:]))
        row = {"kind": "gpt2_train_row", "variant": name, "seq": seq,
               "batch": B, "median_step_s": round(med, 3),
               "tokens_per_s": round(B * seq / med, 1),
               "loss": round(float(loss), 3)}
        rows.append(row)
        print("[sparse_ld]", row, flush=True)
    if len(rows) == 2 and rows[0]["median_step_s"]:
        rows.append({"kind": "gpt2_train_speedup", "seq": seq,
                     "sparse_vs_flash": round(
                         rows[0]["median_step_s"] / rows[1]["median_step_s"],
                         2)})
        print("[sparse_ld]", rows[-1], flush=True)
    return rows


def main():
    enable_persistent_cache()
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "sparse_lowdensity_results.json")
    out = {"kernel": [], "model": []}

    def flush():
        with open(path, "w") as f:
            json.dump(out, f, indent=1)

    out["kernel"] = kernel_rows()
    flush()
    for seq in (8192, 16384):
        out["model"] += model_rows(seq=seq)
        flush()
    print("[sparse_ld] wrote", path, flush=True)


if __name__ == "__main__":
    main()
