"""Int8 serving bench: bf16 vs int8-at-rest decode on the real chip.

Measures what the int8 compute tier exists for (reference int8 inference,
docs/_posts/2021-03-16-mixture-of-quantization ff.): HBM weight footprint
and decode throughput of the whole-loop compiled generate() on a
TransformerLM, bf16 engine vs dtype=int8 engine (QuantDense + Pallas
dequant-GEMM). Writes benchmarks/int8_bench_results.json.

Usage: python benchmarks/int8_bench.py [--layers N] [--embd D] [--tokens T]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def tree_bytes(tree):
    return sum(l.size * l.dtype.itemsize for l in jax.tree_util.tree_leaves(tree))


def bench_engine(engine, ids, n_tokens, repeats=3):
    engine.generate(ids, max_new_tokens=n_tokens)  # compile + warm
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        toks = engine.generate(ids, max_new_tokens=n_tokens)
        times.append(time.perf_counter() - t0)
    dt = min(times)
    n_new = toks.shape[1] - ids.shape[1]
    return {"tokens_per_s": n_new * ids.shape[0] / dt, "elapsed_s": dt,
            "param_bytes": tree_bytes(engine.params)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=24)
    ap.add_argument("--embd", type=int, default=1536)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--tokens", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=1024)
    args = ap.parse_args()

    import deepspeed_tpu
    from deepspeed_tpu.models.transformer_lm import (
        TransformerLM,
        transformer_config,
    )

    cfg = transformer_config("llama", vocab_size=args.vocab,
                             n_embd=args.embd, n_layer=args.layers,
                             n_head=args.heads, max_seq_len=args.seq)
    model = TransformerLM(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, args.vocab, (args.batch, 16)), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, ids,
                        method=model.logits)["params"]
    n_params = sum(l.size for l in jax.tree_util.tree_leaves(params))
    print(f"model: {n_params/1e6:.1f}M params, {args.layers}L {args.embd}d",
          flush=True)

    rows = {}
    for dtype in ("bfloat16", "int8"):
        eng = deepspeed_tpu.init_inference(model, model_parameters=params,
                                           dtype=dtype)
        rows[dtype] = bench_engine(eng, ids, args.tokens)
        print(dtype, rows[dtype], flush=True)
        del eng

    result = {
        "model": {"params_m": n_params / 1e6, "layers": args.layers,
                  "embd": args.embd, "vocab": args.vocab,
                  "batch": args.batch, "decode_tokens": args.tokens},
        "bf16": rows["bfloat16"],
        "int8": rows["int8"],
        "footprint_ratio": rows["int8"]["param_bytes"] /
                           rows["bfloat16"]["param_bytes"],
        "decode_speedup": rows["int8"]["tokens_per_s"] /
                          rows["bfloat16"]["tokens_per_s"],
        "backend": jax.default_backend(),
    }
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "int8_bench_results.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
