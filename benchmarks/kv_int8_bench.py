"""int8 KV-cache decode attention vs bf16 — measured on the real chip.

Decode is HBM-bandwidth bound: every generated token re-reads the whole
live cache. Quantizing the cache to int8 (per-row scales,
``quantize_kv_rows``) halves those bytes; the kernel folds the scales
into the score/probability rows so no dequantized block is ever
materialized (ops/attention/decode_attention.py). This bench times the
kernel at generation-realistic shapes (the 350M flagship head layout and
a GQA serving layout) with the cache fully live.

Run ON the real chip: python benchmarks/kv_int8_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _bench_util import enable_persistent_cache  # noqa: E402

ITERS = 64   # kernel calls per on-device loop (amortizes tunnel dispatch)
REPS = 7     # loop dispatches; median taken


def run_case(B, H, KV, D, S, block=None):
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.ops.attention.decode_attention import (
        decode_attention, pack_int8_sublanes, pick_block_s,
        quantize_kv_rows)

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.bfloat16)
    lengths = jnp.full((B,), S, jnp.int32)  # fully live cache
    k8, ks = quantize_kv_rows(k)
    v8, vs = quantize_kv_rows(v)
    ds = lambda c: c.transpose(0, 1, 3, 2)  # noqa: E731 (B,KV,D,S) layout
    k, v, k8, v8 = ds(k), ds(v), ds(k8), ds(v8)
    if block is None:
        block = pick_block_s(S)

    # time an ON-DEVICE chain of ITERS kernel calls — a single host
    # dispatch per measurement, so the tunnel's ~100 ms per-call latency
    # divides out. Each iteration's q depends on the previous output via
    # a tiny non-foldable term (q + out*1e-30), so the calls serialize
    # and cannot be DCE'd; cache operands are ARGUMENTS (a closure would
    # bake them into the HLO as constants and blow the remote-compile
    # request limit).
    def chain(kernel_call):
        def fn(qq, *ops):
            def body(i, q_carry):
                out = kernel_call(q_carry, *ops)
                return q_carry + out * jnp.asarray(1e-30, out.dtype)
            return jax.lax.fori_loop(0, ITERS, body, qq)
        return jax.jit(fn)

    f_bf16 = chain(lambda qq, kk, vv: decode_attention(
        qq, kk, vv, lengths, block_s=block))
    f_int8 = chain(lambda qq, kk, vv, kss, vss: decode_attention(
        qq, kk, vv, lengths, k_scale=kss, v_scale=vss, block_s=block))

    def med(fn, *ops):
        fn(q, *ops).block_until_ready()  # compile
        walls = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            fn(q, *ops).block_until_ready()
            walls.append(time.perf_counter() - t0)
        return float(np.median(walls)) / ITERS

    t_bf16 = med(f_bf16, k, v)
    t_int8 = med(f_int8, k8, v8, ks, vs)
    # int32-packed container (the kv_cache_packed default): same bytes,
    # free in-kernel bitcast unpack — times any container overhead
    t_i32 = med(f_int8, pack_int8_sublanes(k8), pack_int8_sublanes(v8),
                ks, vs)
    single_bf16 = jax.jit(lambda qq, kk, vv: decode_attention(
        qq, kk, vv, lengths, block_s=block))
    single_int8 = jax.jit(lambda qq, kk, vv, kss, vss: decode_attention(
        qq, kk, vv, lengths, k_scale=kss, v_scale=vss, block_s=block))
    # numerics: int8 output tracks bf16 closely
    err = float(jnp.max(jnp.abs(
        single_int8(q, k8, v8, ks, vs).astype(jnp.float32)
        - single_bf16(q, k, v).astype(jnp.float32))))
    kv_bytes_bf16 = 2 * B * KV * S * D * 2
    kv_bytes_int8 = 2 * B * KV * S * D * 1 + 2 * B * KV * S * 4
    return {
        "B": B, "H": H, "KV": KV, "D": D, "cache_len": S, "block_s": block,
        "bf16_ms": round(t_bf16 * 1e3, 3),
        "int8_ms": round(t_int8 * 1e3, 3),
        "int8_i32packed_ms": round(t_i32 * 1e3, 3),
        "speedup": round(t_bf16 / t_int8, 3),
        "speedup_i32packed": round(t_bf16 / t_i32, 3),
        "kv_mb_bf16": round(kv_bytes_bf16 / 2 ** 20, 1),
        "kv_mb_int8": round(kv_bytes_int8 / 2 ** 20, 1),
        "max_abs_err": round(err, 4),
    }


def run_e2e(key, prompt_len, gen_len, arms=("bf16", "int8"), note="",
            batch=2, smax=8192, batch_by_arm=None):
    """End-to-end generation throughput through the public generate():
    the measurement behind the ``e2e_generate*`` keys. Arms: bf16 cache,
    int8 (the kv_cache_packed int32-container default), int8_s8 (the
    plain-int8 layout, for the container A/B). ``batch_by_arm`` lets the
    capacity-throughput row serve each cache dtype at ITS measured max
    batch (the serving-aggregate comparison)."""
    import jax
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (TransformerConfig,
                                                     TransformerLM)

    rows = []
    for arm in arms:
        B = (batch_by_arm or {}).get(arm, batch)
        prompts = np.random.default_rng(0).integers(
            0, 50257, (B, prompt_len)).astype(np.int32)
        cfg = TransformerConfig(
            vocab_size=50257, max_seq_len=smax, n_embd=1024, n_layer=24,
            n_head=16, kv_cache_quant=arm != "bf16",
            kv_cache_packed=arm != "int8_s8")
        eng = ds.init_inference(TransformerLM(cfg), config={"dtype": "bf16"})
        jax.block_until_ready(  # compile prefill+decode
            eng.generate(prompts, max_new_tokens=gen_len))
        walls = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(
                eng.generate(prompts, max_new_tokens=gen_len))
            walls.append(time.perf_counter() - t0)
        sec = float(np.median(walls))
        rows.append({"kv": arm, "B": B, "gen_s": round(sec, 3),
                     "tok_s": round(B * gen_len / sec, 1),
                     "_raw_tok_s": B * gen_len / sec})
        print(f"[kv_int8] e2e {key} {rows[-1]}", flush=True)
        del eng
    out = {"config": {"max_seq_len": smax, "prompt": prompt_len,
                      "gen": gen_len, "model": "350m-class", "note": note},
           "rows": rows}
    by = {r["kv"]: r.pop("_raw_tok_s") for r in rows}  # ratio from raw,
    # not the display-rounded tok_s
    if "bf16" in by and "int8" in by:
        out["e2e_speedup"] = round(by["int8"] / by["bf16"], 3)
    out_path = os.path.join(os.path.dirname(__file__),
                            "kv_int8_results.json")
    result = json.load(open(out_path)) if os.path.exists(out_path) else {}
    result[key] = out
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(f"[kv_int8] {key} -> {out_path}", flush=True)


def main():
    enable_persistent_cache()
    if "--e2e" in sys.argv:
        run_e2e("e2e_generate", 512, 1024,
                arms=("bf16", "int8", "int8_s8"),
                note="decode-dominated; live 512->1536")
        run_e2e("e2e_generate_long_prompt", 4096, 256,
                note="pre-fix this config OOM-crashed the worker (prefill "
                     "attended over the allocated cache)")
        return
    here = os.path.dirname(os.path.abspath(__file__))

    def capacity_32k_batches():
        """Each arm's measured max batch, read from the capacity
        artifact so a re-measured ladder automatically reflows here."""
        with open(os.path.join(here, "kv_capacity_results_32k.json")) as f:
            caps = json.load(f)["max_batch"]
        return {"bf16": caps["bf16"], "int8": caps["int8"]}

    if "--e2e-32k-arm" in sys.argv:
        # internal: one arm in this process (the 13 GB bf16 cache does
        # not reliably free before the next arm's allocation — same
        # isolation rationale as kv_capacity_bench)
        arm = sys.argv[sys.argv.index("--e2e-32k-arm") + 1]
        run_e2e(f"e2e_serving_32k_{arm}", 512, 128, arms=(arm,),
                smax=32768, batch_by_arm=capacity_32k_batches())
        return
    if "--e2e-32k" in sys.argv:
        # aggregate SERVING throughput at 32k context: each cache dtype
        # runs at its own measured max batch (kv_capacity_results_32k) —
        # the capacity win expressed as tokens/s/chip. One subprocess
        # per arm; merge into a single artifact key and always clean the
        # per-arm temp keys, even when an arm fails.
        import subprocess

        out_path = os.path.join(here, "kv_int8_results.json")
        merged = None
        try:
            for arm in ("bf16", "int8"):
                subprocess.run([sys.executable, os.path.abspath(__file__),
                                "--e2e-32k-arm", arm], check=True, cwd=here)
            result = json.load(open(out_path))
            rows = [result[f"e2e_serving_32k_{arm}"]["rows"][0]
                    for arm in ("bf16", "int8")]
            # ratio from gen_s (3-decimal), not the 1-decimal tok_s
            rate = {r["kv"]: r["B"] * 128 / r["gen_s"] for r in rows}
            merged = {
                "config": {"max_seq_len": 32768, "prompt": 512, "gen": 128,
                           "model": "350m-class",
                           "note": "each arm at its measured max batch at "
                                   "S=32768 (kv_capacity_results_32k.json);"
                                   " aggregate tok/s"},
                "rows": rows,
                "serving_throughput_ratio": round(
                    rate["int8"] / rate["bf16"], 3),
            }
        finally:
            res = json.load(open(out_path))
            for arm in ("bf16", "int8"):
                res.pop(f"e2e_serving_32k_{arm}", None)
            if merged is not None:
                res["e2e_serving_32k"] = merged
            with open(out_path, "w") as f:
                json.dump(res, f, indent=1)
        print(f"[kv_int8] e2e_serving_32k -> {out_path}: "
              f"{res['e2e_serving_32k']}", flush=True)
        return
    out_path = os.path.join(os.path.dirname(__file__),
                            "kv_int8_results.json")
    result = json.load(open(out_path)) if os.path.exists(out_path) else {}
    result.update({"iters": ITERS, "rows": []})
    cases = [
        # 350M-flagship head layout (H=16, D=64), growing cache
        (8, 16, 16, 64, 2048, None),
        (8, 16, 16, 64, 8192, None),
        (8, 16, 16, 64, 16384, None),
        # GQA 4x serving layout (llama-style), long cache
        (4, 32, 8, 128, 8192, None),
        (4, 32, 8, 128, 16384, None),
        # long-context block sweep: grid overhead, not bandwidth, bounds
        # the default 1024 block at 16k — bigger blocks amortize it
        (8, 16, 16, 64, 16384, 2048),
        (8, 16, 16, 64, 16384, 4096),
    ]
    for case in cases:
        row = run_case(*case)
        result["rows"].append(row)
        print(f"[kv_int8] {row}", flush=True)
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
    print(f"[kv_int8] -> {out_path}", flush=True)


if __name__ == "__main__":
    main()
