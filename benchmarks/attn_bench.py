"""Attention crossover benchmark on the real chip.

Measures, per sequence length:
  1. training attention fwd+bwd: Pallas flash attention vs XLA's fused
     attention (the VERDICT crossover table — where does the custom kernel
     win?);
  2. decode: the fused Pallas KV-cache kernel vs the jnp cached path at a
     realistic model width.

Writes JSON to ``benchmarks/attn_bench_results.json`` and prints a table.
Run WITHOUT a platform override (claims the real TPU through the tunnel).
"""

from __future__ import annotations

import functools
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timed(scalar_fn, *args, iters=20):
    """Wall time per iteration of ``scalar_fn(perturbed_args) -> scalar``.

    The N iterations run ON DEVICE inside one jit (fori_loop) with an
    iteration-dependent input perturbation so XLA cannot hoist the body;
    the scalar result is fetched to host, which forces completion even on
    async/tunneled backends where block_until_ready returns early.
    """
    import jax
    import jax.numpy as jnp

    def loop(*a):
        def body(i, acc):
            perturbed = (a[0] + i.astype(a[0].dtype) * 1e-6,) + a[1:]
            return acc + scalar_fn(*perturbed)

        return jax.lax.fori_loop(0, iters, body,
                                 jnp.zeros((), jnp.float32))

    f = jax.jit(loop)
    float(f(*args))  # warmup/compile
    t0 = time.perf_counter()
    out = float(f(*args))
    dt = (time.perf_counter() - t0) / iters
    assert out == out, "nan result"
    return dt


def bench_training_attention(results):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.ops.attention.flash_attention import flash_attention

    H, D = 12, 64
    rng = np.random.default_rng(0)

    def xla_attn(q, k, v):
        s = jnp.einsum("bthd,bshd->bhts", q, k) / math.sqrt(D)
        mask = jnp.tril(jnp.ones((q.shape[1], q.shape[1]), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
        return jnp.einsum("bhts,bshd->bthd", p, v)

    def loss_of(attn):
        def f(q, k, v):
            return attn(q, k, v).astype(jnp.float32).sum()

        grad_f = jax.grad(f, argnums=(0, 1, 2))

        def scalar(q, k, v):
            gq, gk, gv = grad_f(q, k, v)
            return (gq.astype(jnp.float32).sum() +
                    gk.astype(jnp.float32).sum() +
                    gv.astype(jnp.float32).sum())

        return scalar

    for seq in (1024, 2048, 4096, 8192):
        # keep tokens-per-call constant-ish to bound memory
        B = max(1, 8192 // seq)
        shape = (B, seq, H, D)
        q, k, v = (jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
                   for _ in range(3))
        row = {"kind": "train_fwd_bwd", "seq": seq, "batch": B,
               "heads": H, "head_dim": D}
        for name, attn in (("xla", xla_attn),
                           ("flash", functools.partial(flash_attention,
                                                       causal=True))):
            try:
                dt = timed(loss_of(attn), q, k, v)
                row[f"{name}_ms"] = dt * 1e3
                row[f"{name}_tok_s"] = B * seq / dt
            except Exception as e:  # OOM at long seq for the XLA path
                row[f"{name}_ms"] = None
                row[f"{name}_error"] = str(e)[:200]
        if row.get("xla_ms") and row.get("flash_ms"):
            row["flash_speedup"] = row["xla_ms"] / row["flash_ms"]
        results.append(row)
        print(row)


def bench_decode_attention(results):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.ops.attention.decode_attention import (
        decode_attention,
        pick_block_s,
    )

    B, H, D = 8, 16, 128  # 2048-wide model
    rng = np.random.default_rng(0)

    def jnp_decode(q, k, v, length):
        S = k.shape[2]
        s = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                       k.astype(jnp.float32)) / math.sqrt(D)
        s = jnp.where(jnp.arange(S)[None, None, :] < length, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhs,bhsd->bhd", p, v.astype(jnp.float32))

    import functools as ft

    for S in (1024, 2048, 4096, 8192, 16384):
        q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.bfloat16)
        length = jnp.asarray(S - 3, jnp.int32)
        kt = k.transpose(0, 1, 3, 2)  # kernel cache layout (B, KV, D, S)
        vt = v.transpose(0, 1, 3, 2)
        row = {"kind": "decode", "cache_len": S, "batch": B, "heads": H,
               "head_dim": D}

        def kernel_scalar(q, kt, vt, length, block_s):
            # kt/vt already in the kernel's positions-minor (B,KV,D,S)
            return decode_attention(q, kt, vt, length, block_s=block_s) \
                .astype(jnp.float32).sum()

        def jnp_scalar(q, k, v, length):
            return jnp_decode(q, k, v, length).astype(jnp.float32).sum()

        # per-cache-length block sweep: the tuned table in pick_block_s
        # must only contain measured winners
        sweep = {}
        for bs in (256, 512, 1024):
            if bs > S:
                continue
            sweep[bs] = timed(ft.partial(kernel_scalar, block_s=bs),
                              q, kt, vt, length, iters=50) * 1e6
        best_bs = min(sweep, key=sweep.get)
        row["block_sweep_us"] = {str(b): round(t, 1)
                                 for b, t in sweep.items()}
        row["best_block_s"] = best_bs
        row["tuned_block_s"] = pick_block_s(S)
        row["pallas_us"] = sweep[pick_block_s(S)] \
            if pick_block_s(S) in sweep else sweep[best_bs]
        row["jnp_us"] = timed(jnp_scalar, q, k, v, length, iters=50) * 1e6
        row["pallas_speedup"] = row["jnp_us"] / row["pallas_us"]

        # live-length scaling: decode at p << capacity (the realistic
        # generate() regime) — the clamped index maps make the kernel's
        # HBM traffic track p while the dense jnp path always reads S
        short = jnp.asarray(max(S // 8, 1), jnp.int32)
        row["pallas_short_us"] = timed(
            ft.partial(kernel_scalar, block_s=pick_block_s(S)),
            q, kt, vt, short, iters=50) * 1e6
        row["jnp_short_us"] = timed(jnp_scalar, q, k, v, short,
                                    iters=50) * 1e6
        row["pallas_short_speedup"] = row["jnp_short_us"] / \
            row["pallas_short_us"]
        results.append(row)
        print(row)


def main():
    import jax

    print("backend:", jax.default_backend(), jax.devices())
    results = []
    bench_decode_attention(results)
    bench_training_attention(results)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "attn_bench_results.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
    print("wrote", out)


if __name__ == "__main__":
    main()
