"""Shared benchmark plumbing."""

from __future__ import annotations

import os


def enable_persistent_cache():
    """Persistent XLA compile cache — the tunneled remote-compile service
    has multi-hour flaky stretches (BASELINE.md); cached programs survive
    them and reruns. Shared by every benchmark in this directory."""
    import jax

    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                         ".jax_cache")
    try:
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:
        pass
