"""Train a model whose bf16 parameters EXCEED device HBM on one chip.

The measured analog of the reference's ZeRO-Infinity headline ("13B
trainable on one 32 GB V100", docs/_pages/training.md:302): an 8.5B-param
llama-style model — 17.1 GB of bf16 parameters vs 16 GB of HBM (1.07x),
57 GB counting grads+optimizer vs HBM (3.6x) — trains on the single
v5e chip via `zero_optimization.offload_param` streaming
(runtime/zero/param_offload.py).

Placement on this host (125 GB DRAM, ~80 GB free SSD):
  params bf16        17 GB  host DRAM (offload_param.device=cpu)
  fp32 master        34 GB  host DRAM (offload_optimizer.swap_master=false)
  Adam moments       68 GB  NVMe      (offload_optimizer.device=nvme)
  grads fp32         34 GB  host DRAM, freed progressively by the update

Protocol: ONE fixed batch, >=4 steps — the loss must decrease
monotonically (memorization), proving the full fwd/bwd/update loop is
real. Per-phase wall times from the runner's instrumentation; host RSS
sampled per step. Structured like zero_inference_bench.py for the
tunneled-runtime pathologies (single process, sync points only at step
boundaries).

Run ON the real chip (no platform override):
    python benchmarks/param_offload_bench.py [--layers N] [--steps K]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def rss_gb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1e6
    return -1.0


def make_params(model, batch, seed=0):
    """Host param tree WITHOUT running flax init (8.5B fp32 init on a
    single core would dominate the run): eval_shape gives the structure,
    numpy fills it — randn*0.02 for kernels/embeddings, ones for norm
    scales, zeros for biases. Statistically equivalent to the module's
    init for this purpose."""
    import jax
    import ml_dtypes

    rngs = {"params": jax.random.PRNGKey(seed)}
    shapes = jax.eval_shape(lambda: model.init(rngs, batch))["params"]
    rng = np.random.default_rng(seed)

    def fill(path, sds):
        name = str(getattr(path[-1], "key", ""))
        shape, dtype = sds.shape, sds.dtype
        if name == "scale":          # rmsnorm gain
            return np.ones(shape, np.dtype(dtype))
        if name == "bias":
            return np.zeros(shape, np.dtype(dtype))
        n = int(np.prod(shape))
        out = np.empty(n, ml_dtypes.bfloat16)
        CH = 1 << 24
        for lo in range(0, n, CH):      # chunked: no fp32 full-size copy
            hi = min(lo + CH, n)
            out[lo:hi] = (rng.standard_normal(hi - lo, np.float32) *
                          0.02).astype(ml_dtypes.bfloat16)
        return out.reshape(shape)

    return jax.tree_util.tree_map_with_path(fill, shapes)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=34)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--nvme", default="/tmp/ds_param_bench_nvme")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "param_offload_results.json"))
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (
        TransformerLM,
        transformer_config,
    )

    cfg = transformer_config(
        "llama", vocab_size=32000, max_seq_len=args.seq, n_embd=4096,
        n_layer=args.layers, n_head=32, mlp_ratio=3.5, dtype=jnp.bfloat16)
    model = TransformerLM(cfg)

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, (args.batch, args.seq)).astype(np.int32)}

    t0 = time.perf_counter()
    params = make_params(model, batch)
    n_params = sum(int(np.prod(np.shape(l)))
                   for l in jax.tree_util.tree_leaves(params))
    param_gb = sum(np.asarray(l).nbytes
                   for l in jax.tree_util.tree_leaves(params)) / 1e9
    dev = jax.devices()[0]
    hbm_gb = 16.0
    try:
        stats = dev.memory_stats()
        if stats and stats.get("bytes_limit"):
            hbm_gb = stats["bytes_limit"] / 1e9
    except Exception:
        pass
    print(f"[bench] {n_params / 1e9:.2f}B params, {param_gb:.1f} GB bf16 "
          f"vs {hbm_gb:.1f} GB HBM ({param_gb / hbm_gb:.2f}x); init "
          f"{time.perf_counter() - t0:.0f}s rss={rss_gb():.1f} GB",
          flush=True)

    os.makedirs(args.nvme, exist_ok=True)
    t1 = time.perf_counter()
    engine, _, _, _ = ds.initialize(
        model=model, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": args.batch,
            "gradient_accumulation_steps": 1,
            "zero_optimization": {
                "offload_param": {"device": "cpu"},
                "offload_optimizer": {"device": "nvme",
                                      "nvme_path": args.nvme,
                                      "swap_master": False},
            },
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 1e-4, "weight_decay": 0.0}},
            "bf16": {"enabled": True},
            "gradient_clipping": 1.0,
            "steps_per_print": 1,
        })
    del params
    print(f"[bench] engine built in {time.perf_counter() - t1:.0f}s "
          f"rss={rss_gb():.1f} GB", flush=True)

    steps = []
    for i in range(args.steps):
        ts = time.perf_counter()
        loss = float(engine.train_batch(batch=batch))
        wall = time.perf_counter() - ts
        row = {"step": i + 1, "loss": loss, "wall_s": round(wall, 2),
               "rss_gb": round(rss_gb(), 1),
               "grad_norm": float(engine.get_global_grad_norm()),
               "timings": {k: round(v, 2) for k, v in
                           engine._param_offload.last_timings.items()}}
        steps.append(row)
        print(f"[bench] {json.dumps(row)}", flush=True)
        # flush partial rows every step: an hours-long tunnel-bound run
        # that dies late must still leave a committed artifact
        with open(args.out + ".partial", "w") as f:
            json.dump({"steps": steps}, f, indent=1)

    losses = [s["loss"] for s in steps]
    decreasing = all(b < a for a, b in zip(losses, losses[1:]))
    tokens = args.batch * args.seq
    best_wall = min(s["wall_s"] for s in steps[1:]) if len(steps) > 1 \
        else steps[0]["wall_s"]
    result = {
        "model": {"params_b": round(n_params / 1e9, 2),
                  "bf16_gb": round(param_gb, 1),
                  "hbm_gb": round(hbm_gb, 1),
                  "params_vs_hbm": round(param_gb / hbm_gb, 2),
                  "n_layer": cfg.n_layer, "n_embd": cfg.n_embd,
                  "seq": args.seq, "batch": args.batch},
        "placement": {"params": "cpu", "master": "cpu(dram)",
                      "moments": "nvme", "grads": "cpu(progressive)"},
        "steps": steps,
        "loss_decreasing": decreasing,
        "tokens_per_step": tokens,
        "tokens_per_s_best": round(tokens / best_wall, 1),
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(f"[bench] loss_decreasing={decreasing} -> {args.out}", flush=True)
    if not decreasing:
        sys.exit(1)


if __name__ == "__main__":
    main()
