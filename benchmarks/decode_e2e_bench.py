"""End-to-end generation delta from the fused Pallas decode kernel.

A/B/A on a 1B llama-family model: `decode_kernel="off"` vs `"auto"`
vs `"off"` again (order effects on the shared chip are real), whole-loop
compiled generate(), 8x128 new tokens against a 1024-slot cache. This is
the system-level complement to the kernel microbench in attn_bench.py:
generation decodes almost entirely at live length << capacity, the
regime the kernel's DMA clamp targets. Writes decode_e2e_results.json.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer_lm import (
        TransformerLM,
        transformer_config,
    )

    rows = {}
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, 32000, (8, 16)), jnp.int32)
    params = None
    for mode in ("off", "auto", "off_again"):
        cfg = transformer_config(
            "llama", vocab_size=32000, n_embd=1536, n_layer=24, n_head=16,
            max_seq_len=1024, decode_kernel=mode.split("_")[0])
        model = TransformerLM(cfg)
        if params is None:
            params = model.init({"params": jax.random.PRNGKey(0)}, ids,
                                method=model.logits)["params"]
        eng = deepspeed_tpu.init_inference(model, model_parameters=params,
                                           dtype="bfloat16")
        eng.generate(ids, max_new_tokens=128)  # compile + warm
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            eng.generate(ids, max_new_tokens=128)
            times.append(time.perf_counter() - t0)
        rows[mode] = {"tokens_per_s": round(128 * 8 / min(times), 1),
                      "times": [round(t, 2) for t in times]}
        print(mode, rows[mode], flush=True)
        del eng

    result = {
        "kind": "decode_kernel_e2e", "model": "1.0B llama 24Lx1536",
        "batch": 8, "new_tokens": 128, "cache_len": 1024, "rows": rows,
        "speedup_auto_vs_off": round(
            rows["auto"]["tokens_per_s"] /
            max(rows["off"]["tokens_per_s"],
                rows["off_again"]["tokens_per_s"]), 3),
    }
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "decode_e2e_results.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
