"""Block sweep for the bf16-MXU flash kernel at the flagship attention
shape (GPT-2 350M: B10 H16 D64 seq1024) vs XLA's fused attention.

Round-5 follow-up to the r2 crossover table: the kernels previously cast
all MXU operands to fp32 (fraction of peak on v5e); after the bf16-operand
rework this sweep decides whether the flash crossover moves below 2048.

Run ON the real chip: python benchmarks/flash1k_sweep.py
"""

from __future__ import annotations

import functools
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from attn_bench import timed  # noqa: E402  (in-jit fori_loop timing)


def main():
    import jax.numpy as jnp
    import numpy as np

    import jax
    from deepspeed_tpu.ops.attention.flash_attention import flash_attention

    print("backend:", jax.default_backend(), flush=True)
    H, D = 16, 64
    rng = np.random.default_rng(0)

    def xla_attn(q, k, v):
        s = jnp.einsum("bthd,bshd->bhts", q, k) / math.sqrt(D)
        mask = jnp.tril(jnp.ones((q.shape[1], q.shape[1]), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
        return jnp.einsum("bhts,bshd->bthd", p, v)

    def loss_of(attn):
        def f(q, k, v):
            return attn(q, k, v).astype(jnp.float32).sum()

        grad_f = jax.grad(f, argnums=(0, 1, 2))

        def scalar(q, k, v):
            gq, gk, gv = grad_f(q, k, v)
            return (gq.astype(jnp.float32).sum() +
                    gk.astype(jnp.float32).sum() +
                    gv.astype(jnp.float32).sum())

        return scalar

    results = []
    for seq, B in ((1024, 10), (2048, 4)):
        shape = (B, seq, H, D)
        q, k, v = (jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
                   for _ in range(3))
        row = {"seq": seq, "batch": B, "heads": H, "head_dim": D}
        row["xla_ms"] = timed(loss_of(xla_attn), q, k, v) * 1e3
        sweep = {}
        for bq in (128, 256, 512):
            for bk in (128, 256, 512, 1024):
                if bq > seq or bk > seq:
                    continue
                fn = functools.partial(flash_attention, causal=True,
                                       block_q=bq, block_k=bk)
                try:
                    sweep[f"{bq}x{bk}"] = round(
                        timed(loss_of(fn), q, k, v) * 1e3, 3)
                except Exception as e:  # noqa: BLE001
                    sweep[f"{bq}x{bk}"] = str(e)[:80]
                print(seq, f"{bq}x{bk}", sweep[f"{bq}x{bk}"], flush=True)
        numeric = {k2: t for k2, t in sweep.items()
                   if isinstance(t, float)}
        row["flash_sweep_ms"] = sweep
        if numeric:
            best = min(numeric, key=numeric.get)
            row["best_blocks"] = best
            row["best_flash_ms"] = numeric[best]
            row["flash_speedup_vs_xla"] = round(
                row["xla_ms"] / numeric[best], 3)
        results.append(row)
        print(row, flush=True)

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "flash1k_sweep_results.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote", out, flush=True)


if __name__ == "__main__":
    main()
