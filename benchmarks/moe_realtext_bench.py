"""MoE quality point on REAL text (VERDICT r4 next-#6).

The reference's MoE headline is quality-at-lower-cost
(docs/_posts/2021-12-09-deepspeed-moe-nlg.md:40): adding experts buys
model quality without adding (much) step time. The repo-native analog,
measured end-to-end on the committed real-prose fixture (byte vocab —
zero-egress forbids a pretrained BPE):

* ``dense``    — GPT with 4n MLPs everywhere.
* ``moe_top2`` — every 2nd block is an 8-expert GShard top-2 layer
  (capacity 1.25): ~2.5x the parameters.

Both train the SAME step budget on the same data order; the claim is
``val_ppl(moe) <= val_ppl(dense)`` at equal steps, with per-expert token
shares staying spread (the round-4 random-token probe collapsed to 2/8 —
real text with its Zipfian structure is the fair test of the aux loss).

Run ON the chip: python benchmarks/moe_realtext_bench.py [--steps N]
"""

from __future__ import annotations

import argparse
import json
import lzma
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

FIXTURES = os.path.join(os.path.dirname(__file__), "..", "tests", "model",
                        "fixtures")


def load(split):
    with lzma.open(os.path.join(FIXTURES, f"realtext_{split}.txt.xz"),
                   "rt") as f:
        return np.frombuffer(f.read().encode("utf-8"), np.uint8)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--eval-every", type=int, default=50)
    args = ap.parse_args()

    import jax

    from _bench_util import enable_persistent_cache

    enable_persistent_cache()

    import jax.numpy as jnp

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.gpt_moe import GPTMoEConfig, GPTMoEModel
    from deepspeed_tpu.moe.layer import MoE

    train, val = load("train"), load("val")
    rng_val = np.random.default_rng(7)

    def batch_from(data, seed_rng):
        starts = seed_rng.integers(0, len(data) - args.seq - 1, args.batch)
        return {"input_ids": np.stack(
            [data[s:s + args.seq] for s in starts]).astype(np.int32)}

    val_batches = [batch_from(val, rng_val) for _ in range(4)]

    kw = dict(vocab_size=256, n_positions=args.seq, n_embd=256, n_layer=6,
              n_head=8, capacity_factor=1.25, drop_tokens=True,
              dtype=jnp.bfloat16)

    def run(kind):
        cfg = GPTMoEConfig(moe_every=0, **kw) if kind == "dense" else \
            GPTMoEConfig(moe_every=2, num_experts=8, k=2, **kw)
        model = GPTMoEModel(cfg)
        engine, _, _, _ = ds.initialize(
            model=model,
            config={"train_micro_batch_size_per_gpu": args.batch,
                    "gradient_accumulation_steps": 1,
                    "zero_optimization": {"stage": 0},
                    "optimizer": {"type": "AdamW",
                                  "params": {"lr": 3e-4,
                                             "weight_decay": 0.01}},
                    "scheduler": {"type": "WarmupLR",
                                  "params": {"warmup_num_steps": 50}},
                    "bf16": {"enabled": True},
                    "gradient_clipping": 1.0, "steps_per_print": 10 ** 9})

        def eval_loss(params, batch):
            out = model.apply({"params": params}, batch, deterministic=True)
            return out[0] if isinstance(out, tuple) else out

        eval_fn = jax.jit(eval_loss)

        def aux_eval(params, batch):
            return model.apply({"params": params}, batch,
                               deterministic=True)[1]

        aux_fn = jax.jit(aux_eval) if kind != "dense" else None

        def val_ppl():
            losses = [float(eval_fn(engine.state["params"], b))
                      for b in val_batches]
            return float(np.exp(np.mean(losses)))

        rng = np.random.default_rng(0)  # same data order for both models
        traj, aux_traj, walls = [], [], []
        for step in range(1, args.steps + 1):
            b = batch_from(train, rng)
            t0 = time.perf_counter()
            loss = float(engine.train_batch(batch=b))
            walls.append(time.perf_counter() - t0)
            if aux_fn is not None and \
                    (step % 10 == 0 or step == 1):
                aux_traj.append(
                    {"step": step,
                     "aux": round(float(aux_fn(engine.state["params"], b)),
                                  5)})
            if step == 1 or step % args.eval_every == 0:
                traj.append({"step": step, "train_loss": round(loss, 4),
                             "val_ppl": round(val_ppl(), 3)})
                print(f"[moe_realtext] {kind} {traj[-1]}", flush=True)

        row = {
            "kind": kind,
            "params_m": round(engine.num_parameters / 1e6, 1),
            "median_step_s": round(float(np.median(walls[3:])), 4),
            "trajectory": traj,
            "final_val_ppl": traj[-1]["val_ppl"],
            "aux_loss_trajectory": aux_traj or None,
        }
        if kind != "dense":
            # per-expert token shares on a REAL-text probe batch after
            # training (the round-4 missing `realtext_balance` evidence)
            import flax

            probe = batch_from(val, np.random.default_rng(11))

            def capture(p, batch):
                return model.apply(
                    {"params": p}, batch, deterministic=True,
                    capture_intermediates=lambda m, _: isinstance(m, MoE))

            _, inter = jax.jit(capture)(engine.state["params"], probe)
            flat = flax.traverse_util.flatten_dict(inter["intermediates"])
            shares = {}
            for path, vals in flat.items():
                if path[-1] == "__call__":
                    _, _, exp_counts = vals[0]
                    v = np.asarray(exp_counts, np.float64)
                    shares["/".join(path[:-1])] = (v / v.sum()).round(
                        4).tolist()
            row["realtext_expert_token_shares"] = shares
            row["min_expert_share"] = round(
                min(min(s) for s in shares.values()), 4)
        return row

    result = {"config": {**kw, "dtype": "bfloat16", "batch": args.batch,
                         "steps": args.steps,
                         "corpus": "real prose fixture (byte vocab)"},
              "rows": []}
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "moe_realtext_results.json")

    for kind in ("dense", "moe_top2"):
        result["rows"].append(run(kind))
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)

    dense, moe = result["rows"]
    result["moe_ppl_le_dense_at_equal_steps"] = \
        moe["final_val_ppl"] <= dense["final_val_ppl"]
    result["step_time_ratio"] = round(
        moe["median_step_s"] / dense["median_step_s"], 3)
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(f"[moe_realtext] dense ppl {dense['final_val_ppl']} vs moe "
          f"{moe['final_val_ppl']} (params {dense['params_m']}M vs "
          f"{moe['params_m']}M, step x{result['step_time_ratio']}) -> "
          f"{out_path}", flush=True)


if __name__ == "__main__":
    main()
