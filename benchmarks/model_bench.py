"""North-star model benchmarks on the real chip (BASELINE.json rows).

Measures steady-state training throughput (tokens/s/chip) and MFU for the
largest dense models that fit one v5e chip, plus the offload path with the
device step and the host (CPU-Adam) step timed SEPARATELY — so the
tunnel-attached host transfers are isolated from the on-VM projection.

    python benchmarks/model_bench.py --model 350m
    python benchmarks/model_bench.py --model 1.3b --offload

Writes/updates ``benchmarks/model_bench_results.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _enable_persistent_cache():
    import jax

    cache_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)

V5E_PEAK_TFLOPS = 197.0  # bf16

MODELS = {
    "125m": dict(n_embd=768, n_layer=12, n_head=12),
    "350m": dict(n_embd=1024, n_layer=24, n_head=16),
    "760m": dict(n_embd=1536, n_layer=24, n_head=16),
    "1.3b": dict(n_embd=2048, n_layer=24, n_head=16),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="350m", choices=sorted(MODELS))
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--mbs", type=int, default=8)
    ap.add_argument("--gas", type=int, default=8)
    ap.add_argument("--stage", type=int, default=2)
    ap.add_argument("--offload", action="store_true")
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--no-remat", action="store_true",
                    help="disable activation checkpointing (fits smaller runs)")
    ap.add_argument("--remat-policy", default="dots",
                    choices=["full", "dots", "dots_plain"])
    ap.add_argument("--flash", default="auto",
                    choices=["auto", "on", "off"],
                    help="Pallas flash attention kernel selection")
    ap.add_argument("--fused-ln", default="auto",
                    choices=["auto", "on", "off"],
                    help="fused LayerNorm->matmul Pallas kernel (ln_linear)")
    args = ap.parse_args()
    _enable_persistent_cache()

    import jax
    import numpy as np
    import jax.numpy as jnp

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    spec = MODELS[args.model]
    flash = {"auto": "auto", "on": True, "off": False}[args.flash]
    fused = {"auto": "auto", "on": True, "off": False}[args.fused_ln]
    cfg = GPT2Config(vocab_size=50257, n_positions=args.seq,
                     dtype=jnp.bfloat16, remat=not args.no_remat,
                     remat_policy=args.remat_policy,
                     use_flash_attention=flash, fused_ln_linear=fused,
                     **spec)
    config = {
        "train_micro_batch_size_per_gpu": args.mbs,
        "gradient_accumulation_steps": args.gas,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": args.stage},
        "optimizer": {"type": "Adam",
                      "params": {"lr": 2e-4, "weight_decay": 0.1}},
        "gradient_clipping": 1.0,
        "steps_per_print": 10 ** 9,
    }
    if args.offload:
        config["zero_optimization"]["offload_optimizer"] = {"device": "cpu"}

    engine, _, _, _ = ds.initialize(model=GPT2LMHeadModel(cfg), config=config)
    rng = np.random.default_rng(0)

    def batch():
        return {"input_ids": rng.integers(
            0, cfg.vocab_size,
            (engine.train_batch_size(), args.seq)).astype(np.int32)}

    # compile + warmup. float(loss) — NOT block_until_ready — forces
    # completion: on the tunneled runtime block_until_ready can return
    # early (attn_bench.timed documents the same), which with a warm
    # compile cache turns the timing loop into dispatch-only nonsense.
    t0 = time.perf_counter()
    loss = float(engine.train_batch(batch=batch()))
    compile_s = time.perf_counter() - t0
    loss = float(engine.train_batch(batch=batch()))

    tokens_per_step = engine.train_batch_size() * args.seq
    n_params = engine.num_parameters

    row = {
        "model": args.model, "params_m": round(n_params / 1e6, 1),
        "seq": args.seq, "mbs": args.mbs, "gas": args.gas,
        "zero_stage": args.stage, "offload": bool(args.offload),
        "remat": (args.remat_policy if not args.no_remat else "off"),
        "flash": args.flash, "fused_ln": args.fused_ln,
        "compile_s": round(compile_s, 1),
    }

    if args.offload:
        # split timing: device grads step vs host optimizer step — the
        # host side crosses the HTTP tunnel here but is PCIe on a TPU-VM,
        # so the split is what makes the on-VM projection evidence
        device_s, host_s = [], []
        for _ in range(args.steps):
            t0 = time.perf_counter()
            engine.state, grads_dev, metrics = engine._jit_offload_grads(
                engine.state, engine._stack_micro_batches(batch()))
            jax.block_until_ready(grads_dev)
            t1 = time.perf_counter()
            engine._host_optimizer_step(grads_dev, metrics)
            host_s.append(time.perf_counter() - t1)
            device_s.append(t1 - t0)
        device_avg = float(np.mean(device_s))
        host_avg = float(np.mean(host_s))
        row.update({
            "device_step_s": round(device_avg, 3),
            "host_step_s_tunnel": round(host_avg, 3),
            "tok_s_device_only": round(tokens_per_step / device_avg, 1),
            "note": "host step crosses the HTTP tunnel on this harness; "
                    "on a TPU-VM the same transfers ride PCIe",
        })
        tok_s = tokens_per_step / device_avg  # on-VM projection upper bound
    else:
        t0 = time.perf_counter()
        for _ in range(args.steps):
            loss = engine.train_batch(batch=batch())
        loss = float(loss)  # forces completion (see warmup note)
        dt = (time.perf_counter() - t0) / args.steps
        tok_s = tokens_per_step / dt
        row["step_s"] = round(dt, 3)

    # Two accountings, both stated (VERDICT r2 weak #1):
    #  - 6N: the reference's convention (attention matmuls uncounted) —
    #    under-reports real work, worse with seq.
    #  - with-attention: + causal attention matmul FLOPs, 6·L·S·d per token
    #    fwd+bwd (QK^T and AV are each 2·S·d fwd per layer per token; x3 for
    #    fwd+bwd; x0.5 causal — only the lower triangle is real work, and the
    #    flash kernel skips the rest, so counting full S^2 would inflate MFU).
    #    Remat recompute is NOT counted in either (model FLOPs, not hardware).
    L, d = spec["n_layer"], spec["n_embd"]
    attn_flops_tok = 6 * L * args.seq * d
    model_tflops = 6 * n_params * tok_s / 1e12
    tflops_attn = (6 * n_params + attn_flops_tok) * tok_s / 1e12
    row.update({
        "tokens_per_s_chip": round(tok_s, 1),
        "model_tflops": round(model_tflops, 1),
        "mfu_pct": round(100 * model_tflops / V5E_PEAK_TFLOPS, 1),
        "model_tflops_attn": round(tflops_attn, 1),
        "mfu_attn_pct": round(100 * tflops_attn / V5E_PEAK_TFLOPS, 1),
        "loss": float(loss) if not args.offload else None,
    })
    print(json.dumps(row))

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "model_bench_results.json")
    rows = []
    if os.path.exists(out):
        with open(out) as f:
            rows = json.load(f)
    rows.append(row)
    with open(out, "w") as f:
        json.dump(rows, f, indent=2)


if __name__ == "__main__":
    main()
