"""Single-chip MoE vs FLOP-matched dense — measured (VERDICT r3 #5).

Anchors the reference's MoE claims with on-chip numbers
(docs/_posts/2021-12-09-deepspeed-moe-nlg.md:40 — "same quality at 5x
lower training cost" rests on MoE adding parameters, not step time):

* ``dense``      — GPT with 4n MLPs everywhere (moe_every=0).
* ``moe_top1``   — every 2nd block is 8-expert Switch-style top-1,
  capacity 1.25. Active FLOPs are IDENTICAL to ``dense`` (each token
  visits one 4n expert), so (t_moe1 - t_dense)/t_dense IS the
  gating+dispatch overhead — the cost of the router, the capacity
  sort/scatter, and the einsum dispatch, isolated.
* ``moe_top2``   — GShard top-2, capacity 1.25: the reference's NLG
  recipe shape; 2x active expert FLOPs on MoE blocks, 8x the MLP
  parameters of its active compute.

Also records the aux-loss (load-balance) trajectory and per-expert token
shares for top-2 over 30 training steps — the router must spread load,
not collapse onto one expert.

Run ON the real chip: python benchmarks/moe_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

B, T = 16, 1024
STEPS_TIMED = 8
STEPS_WARM = 3


def build(kind, dispatch_mode="index"):
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt_moe import GPTMoEConfig, GPTMoEModel

    kw = dict(vocab_size=32768, n_positions=T, n_embd=1024, n_layer=8,
              n_head=16, capacity_factor=1.25, drop_tokens=True,
              moe_dispatch_mode=dispatch_mode, dtype=jnp.bfloat16)
    if kind == "dense":
        cfg = GPTMoEConfig(moe_every=0, **kw)
    elif kind == "moe_top1":
        cfg = GPTMoEConfig(moe_every=2, num_experts=8, k=1, **kw)
    elif kind == "moe_top2":
        cfg = GPTMoEConfig(moe_every=2, num_experts=8, k=2, **kw)
    return GPTMoEModel(cfg)


def run(kind, steps=STEPS_WARM + STEPS_TIMED, record_aux=False,
        dispatch_mode="index"):
    import jax

    import deepspeed_tpu as ds

    model = build(kind, dispatch_mode)
    engine, _, _, _ = ds.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": B,
                "gradient_accumulation_steps": 1,
                "zero_optimization": {"stage": 0},
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
                "bf16": {"enabled": True}, "steps_per_print": 10 ** 9})
    rng = np.random.default_rng(0)
    batches = [{"input_ids": rng.integers(0, 32768, (B, T)).astype(np.int32)}
               for _ in range(steps)]

    aux_fn = None
    if record_aux:
        import jax.numpy as jnp

        def aux_eval(params, batch):
            loss, aux = model.apply({"params": params}, batch,
                                    deterministic=True)
            return aux

        aux_fn = jax.jit(aux_eval)

    walls, aux_traj = [], []
    n_params = None
    for i, b in enumerate(batches):
        t0 = time.perf_counter()
        loss = engine.train_batch(batch=b)
        jax.block_until_ready(loss)
        walls.append(time.perf_counter() - t0)
        if record_aux:
            aux_traj.append(float(aux_fn(engine.state["params"], b)))
        if n_params is None:
            n_params = engine.num_parameters
    timed = walls[STEPS_WARM:]
    med = float(np.median(timed))
    return {
        "kind": kind,
        "dispatch_mode": dispatch_mode if kind != "dense" else None,
        "params_m": round(n_params / 1e6, 1),
        "median_step_s": round(med, 4),
        "tokens_per_s": round(B * T / med, 1),
        "loss_final": float(np.round(float(loss), 4)),
        "aux_trajectory": [round(a, 5) for a in aux_traj] or None,
    }


def expert_balance():
    """Per-expert token shares after 30 top-2 training steps on one fixed
    batch distributionally: the router must spread load."""
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.moe.layer import MoE

    model = build("moe_top2")
    engine, _, _, _ = ds.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": B,
                "gradient_accumulation_steps": 1,
                "zero_optimization": {"stage": 0},
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
                "bf16": {"enabled": True}, "steps_per_print": 10 ** 9})
    import jax.numpy as jnp  # noqa: F401

    def aux_eval(params, batch):
        return model.apply({"params": params}, batch,
                           deterministic=True)[1]

    aux_fn = jax.jit(aux_eval)
    rng = np.random.default_rng(1)
    aux_traj = []
    for _ in range(30):
        b = {"input_ids": rng.integers(0, 32768, (B, T)).astype(np.int32)}
        engine.train_batch(batch=b)
        aux_traj.append(float(aux_fn(engine.state["params"], b)))

    # fish the expert counts out of every MoE block with a probe apply
    import flax

    probe = {"input_ids": rng.integers(0, 32768, (B, T)).astype(np.int32)}

    counts = {}

    # params as an ARGUMENT — a closure would bake 370M weights into the
    # HLO as constants (a program the remote-compile service rejects)
    def capture(p, batch):
        return model.apply({"params": p}, batch, deterministic=True,
                           capture_intermediates=lambda m, _: isinstance(m, MoE))

    out, inter = jax.jit(capture)(engine.state["params"], probe)
    flat = flax.traverse_util.flatten_dict(inter["intermediates"])
    for path, vals in flat.items():
        if path[-1] == "__call__":
            _, _, exp_counts = vals[0]
            counts["/".join(path[:-1])] = np.asarray(exp_counts, np.float64)
    shares = {k: (v / v.sum()).round(4).tolist() for k, v in counts.items()}
    return aux_traj, shares


from _bench_util import enable_persistent_cache as _enable_cache  # noqa: E402


def main():
    _enable_cache()
    out_path = os.path.join(os.path.dirname(__file__),
                            "moe_bench_results.json")
    result = {
        "config": {"batch": B, "seq": T, "n_embd": 1024, "n_layer": 8,
                   "experts": 8, "capacity_factor": 1.25},
        "rows": [],
    }

    def flush():
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)

    # A/B the two dispatch materializations at the same routing decisions:
    # "einsum" = the reference's dense one-hot form, "index" = the
    # TPU-native scatter/gather default (moe/sharded_moe.py module doc)
    for kind, mode in (("dense", "index"),
                       ("moe_top1", "einsum"), ("moe_top1", "index"),
                       ("moe_top2", "einsum"), ("moe_top2", "index")):
        result["rows"].append(run(kind, dispatch_mode=mode))
        print(f"[moe_bench] row done: {result['rows'][-1]}", flush=True)
        flush()  # partial results survive tunnel outages
    rows = result["rows"]
    by = {(r["kind"], r["dispatch_mode"]): r["median_step_s"] for r in rows}
    dense_t = by[("dense", None)]
    moe1_t = by[("moe_top1", "index")]
    overhead_pct = 100.0 * (moe1_t - dense_t) / dense_t
    result["gating_dispatch_overhead_pct"] = round(overhead_pct, 1)
    result["index_vs_einsum_speedup"] = {
        k: round(by[(k, "einsum")] / by[(k, "index")], 3)
        for k in ("moe_top1", "moe_top2")}
    flush()
    try:
        aux_traj, shares = expert_balance()
        result["top2_aux_loss_trajectory"] = [round(a, 4) for a in aux_traj]
        result["top2_expert_token_shares"] = shares
    except Exception as e:  # the balance probe is additive — keep the rows
        result["balance_error"] = str(e)[:200]
    flush()
    for r in rows:
        mode = f" [{r['dispatch_mode']}]" if r["dispatch_mode"] else ""
        print(f"[moe_bench] {r['kind']}{mode}: {r['params_m']}M params, "
              f"{r['tokens_per_s']} tok/s (step {r['median_step_s']}s)",
              flush=True)
    print(f"[moe_bench] gating+dispatch overhead (top1 vs FLOP-matched "
          f"dense): {overhead_pct:.1f}%", flush=True)
    print(f"[moe_bench] -> {out_path}", flush=True)


if __name__ == "__main__":
    main()
