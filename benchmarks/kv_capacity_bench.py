"""Servable capacity at long context: bf16 vs int8 KV cache — measured.

The KV cache dominates serving memory at long context (GPT-2 350M at
S=16384: ~1.6 GB per sequence in bf16, 24 layers of (16, 16384, 64)
K+V — vs 0.7 GB of weights). ``kv_cache_quant=True`` halves it. This
bench walks a batch-size ladder on the real chip and records the
largest batch each cache dtype can actually serve (allocate full cache,
prefill, decode a few tokens) at max_seq_len=16384.

Run ON the real chip: python benchmarks/kv_capacity_bench.py
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _bench_util import enable_persistent_cache  # noqa: E402

SEQ = 16384
PROMPT = 64
NEW_TOKENS = 8


def try_batch(B: int, quant: bool) -> bool:
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (TransformerConfig,
                                                     TransformerLM)

    cfg = TransformerConfig(vocab_size=50257, max_seq_len=SEQ, n_embd=1024,
                            n_layer=24, n_head=16, kv_cache_quant=quant)
    eng = ds.init_inference(TransformerLM(cfg), config={"dtype": "bf16"})
    prompts = np.random.default_rng(0).integers(
        0, 50257, (B, PROMPT)).astype(np.int32)
    for attempt in range(2):
        try:
            toks = eng.generate(prompts, max_new_tokens=NEW_TOKENS)
            jax.block_until_ready(toks)
            return True
        except Exception as e:  # noqa: BLE001
            msg = str(e)
            if "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg \
                    or "Ran out of memory" in msg:
                return False
            # the tunnel's remote-compile reports HBM-infeasible programs
            # as HTTP 500 (OOM detail only in the server log); retry once
            # to rule out a transient outage, then count it infeasible
            if "HTTP 500" in msg and attempt == 0:
                continue
            if "HTTP 500" in msg:
                print(f"[kv_capacity] counted infeasible on persistent "
                      f"HTTP 500: {msg[:160]}", flush=True)
                return False
            raise
    return False


def main():
    enable_persistent_cache()
    out_path = os.path.join(os.path.dirname(__file__),
                            "kv_capacity_results.json")
    result = {"seq": SEQ, "model": "gpt2-350m-class (24L, 1024d, 16h)",
              "ladder": {}, "max_batch": {}}
    # GPT-2 350M-class at S=16384: KV is ~1.6 GB/sequence in bf16
    # (24L x 2 x 16h x 16384 x 64 x 2B); ladders start at 1 and run past
    # the expected boundary so a rung is never reported as the maximum
    # merely because the ladder ended there
    for quant, label, ladder in ((False, "bf16", (1, 2, 3, 4, 5, 6)),
                                 (True, "int8", (1, 2, 3, 4, 5, 6, 7))):
        rows = {}
        best = 0
        for B in ladder:
            ok = try_batch(B, quant)
            rows[B] = ok
            print(f"[kv_capacity] {label} B={B}: {'ok' if ok else 'OOM'}",
                  flush=True)
            if ok:
                best = B
            else:
                break
        result["ladder"][label] = rows
        result["max_batch"][label] = best
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
    bf, i8 = result["max_batch"]["bf16"], result["max_batch"]["int8"]
    result["capacity_ratio"] = round(i8 / bf, 2) if bf else None
    result["finding"] = (
        "The e2e ladder is capped by the prefill->decode dispatch "
        "boundary, not by steady-state cache bytes: when the decode-scan "
        "program is compiled, the prefill-produced cache is still live "
        "and the compile-time HBM accounting does not credit the "
        "dispatch-time donation of the int8 cache carries, so both "
        "dtypes top out near the same batch. Steady-state KV memory "
        "halves as designed (kv_int8_results.json kv_mb columns); "
        "closing the boundary accounting is engine future work.")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(f"[kv_capacity] max batch at seq {SEQ}: bf16={bf} int8={i8} "
          f"-> {result['capacity_ratio']}x", flush=True)


if __name__ == "__main__":
    main()
