"""Servable capacity at long context: bf16 vs int8 KV cache — measured.

The KV cache dominates serving memory at long context (GPT-2 350M-class:
~100 KB per position per sequence in bf16 — ~1.6 GB/sequence at 16k,
~3.2 GB at 32k — vs ~0.7 GB of weights). ``kv_cache_quant=True`` halves
it. This bench walks a batch-size ladder on the real chip and records
the largest batch each cache dtype can actually serve (allocate full
cache, prefill, decode tokens) at ``max_seq_len = KV_CAPACITY_SEQ``
(default 16384; 32768 writes the suffixed artifact).

Each trial runs in its OWN subprocess: earlier trials' device buffers
must not change later trials' headroom. The engine AOT-compiles the
decode program before prefill buffers go live (inference/engine.py
``_compile_decode_scan``), so the compile-time HBM check is not
inflated by transient double-residency at the prefill→decode boundary.

Run ON the real chip: [KV_CAPACITY_SEQ=32768] python benchmarks/kv_capacity_bench.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

SEQ = int(os.environ.get("KV_CAPACITY_SEQ", 16384))  # 32768 for the
# long-context row (writes kv_capacity_results_32k.json)
PROMPT = 64
NEW_TOKENS = 8

TRIAL = """
import sys
sys.path.insert(0, {repo!r}); sys.path.insert(0, {bench!r})
from _bench_util import enable_persistent_cache
enable_persistent_cache()
import numpy as np
import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer_lm import (TransformerConfig,
                                                 TransformerLM)
cfg = TransformerConfig(vocab_size=50257, max_seq_len={seq}, n_embd=1024,
                        n_layer=24, n_head=16, kv_cache_quant={quant},
                        kv_cache_packed={packed})
eng = ds.init_inference(TransformerLM(cfg), config={{"dtype": "bf16"}})
prompts = np.random.default_rng(0).integers(
    0, 50257, ({batch}, {prompt})).astype(np.int32)
toks = eng.generate(prompts, max_new_tokens={new})
import jax; jax.block_until_ready(toks)
print("TRIAL_OK", toks.shape)
"""


OOM_MARKS = ("RESOURCE_EXHAUSTED", "Out of memory", "Ran out of memory",
             "Exceeded hbm capacity")


def try_batch(B: int, quant: bool, packed: bool = True) -> bool:
    """True = serves; False = HBM-infeasible. Infra failures (timeouts,
    persistent non-OOM errors) RAISE — they must never be recorded as a
    measured capacity boundary."""
    here = os.path.dirname(os.path.abspath(__file__))
    code = TRIAL.format(repo=os.path.dirname(here), bench=here, seq=SEQ,
                        quant=quant, packed=packed, batch=B, prompt=PROMPT,
                        new=NEW_TOKENS)
    for attempt in range(2):
        try:
            proc = subprocess.run([sys.executable, "-c", code], timeout=900,
                                  capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            raise RuntimeError(
                f"trial B={B} quant={quant} timed out (900s) — infra, "
                f"not a capacity result")
        if "TRIAL_OK" in proc.stdout:
            return True
        err = proc.stderr or ""
        if any(m in err for m in OOM_MARKS):
            return False
        # the tunnel's remote-compile reports HBM-infeasible programs as
        # HTTP 500 with the OOM detail in its own log stream; it also
        # 500s transiently — retry once before reading it as infeasible
        if "HTTP 500" in err:
            if attempt == 0:
                continue
            print(f"[kv_capacity]   persistent HTTP 500 at B={B} "
                  f"(OOM detail in server log) — counted infeasible",
                  flush=True)
            return False
        tail = " | ".join(err.strip().splitlines()[-3:])[-300:]
        raise RuntimeError(
            f"trial B={B} quant={quant} packed={packed} failed for a "
            f"non-OOM reason: {tail}")
    return False


def main():
    suffix = "" if SEQ == 16384 else f"_{SEQ // 1024}k"
    out_path = os.path.join(os.path.dirname(__file__),
                            f"kv_capacity_results{suffix}.json")
    result = {"seq": SEQ, "model": "gpt2-350m-class (24L, 1024d, 16h)",
              "ladder": {}, "max_batch": {}}
    # ~100 KB/position/sequence bf16 KV, ~55 KB int8 (cache + scales);
    # ladders run past the expected boundary so a rung is never reported
    # as the maximum merely because the ladder ended there (gap-walk +
    # climb logic below closes any remainder). Arms:
    #   bf16     — full-precision cache
    #   int8_s8  — plain-int8 layout (the round-5 double-buffering
    #              negative; fixed by the carry-DUS scan, kept for A/B)
    #   int8     — the kv_cache_packed int32 container (default)
    scale = 16384 / SEQ  # halve the rungs when the cache doubles
    rung = lambda b: max(1, int(b * scale))  # noqa: E731
    for quant, packed, label, ladder in (
            (False, True, "bf16", tuple(dict.fromkeys(
                rung(b) for b in (3, 4, 5, 6, 7, 8, 9)))),
            (True, False, "int8_s8", tuple(dict.fromkeys(
                rung(b) for b in (4, 6, 8, 10, 12, 14, 16)))),
            (True, True, "int8", tuple(dict.fromkeys(
                rung(b) for b in (4, 6, 8, 10, 12, 13, 14, 15, 16, 18))))):
        rows = {}
        best, first_fail = 0, None
        for B in ladder:
            ok = try_batch(B, quant, packed)
            rows[B] = ok
            print(f"[kv_capacity] {label} B={B}: {'ok' if ok else 'OOM'}",
                  flush=True)
            if ok:
                best = B
            else:
                first_fail = B
                break
        if best == 0 and first_fail is not None:
            # the ladder's first rung already failed; walk down so the
            # reported max is measured, not assumed
            for B in range(first_fail - 1, 0, -1):
                ok = try_batch(B, quant, packed)
                rows[B] = ok
                print(f"[kv_capacity] {label} B={B}: "
                      f"{'ok' if ok else 'OOM'}", flush=True)
                if ok:
                    best = B
                    break
        elif first_fail is not None and first_fail - best > 1:
            # the failure landed past a ladder gap: walk the gap upward so
            # max_batch is the true boundary, never a rung artifact
            for B in range(best + 1, first_fail):
                ok = try_batch(B, quant, packed)
                rows[B] = ok
                print(f"[kv_capacity] {label} B={B}: "
                      f"{'ok' if ok else 'OOM'}", flush=True)
                if ok:
                    best = B
                else:
                    break
        elif first_fail is None:
            # every rung passed — keep climbing until a measured failure,
            # capped at 2x the ladder's last rung (each trial costs
            # minutes; past the cap the arm is reported as bounded)
            B, cap = best + 1, 2 * ladder[-1]
            while B <= cap:
                ok = try_batch(B, quant, packed)
                rows[B] = ok
                print(f"[kv_capacity] {label} B={B}: "
                      f"{'ok' if ok else 'OOM'}", flush=True)
                if not ok:
                    break
                best = B
                B += 1
            else:
                result.setdefault("bounded", []).append(label)
                print(f"[kv_capacity] {label}: still serving at the "
                      f"B={cap} climb cap — max_batch is a lower bound",
                      flush=True)
        result["ladder"][label] = rows
        result["max_batch"][label] = best
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
    bf, i8 = result["max_batch"]["bf16"], result["max_batch"]["int8"]
    result["capacity_ratio"] = round(i8 / bf, 2) if bf else None
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(f"[kv_capacity] max batch at seq {SEQ}: bf16={bf} int8={i8} "
          f"-> {result['capacity_ratio']}x", flush=True)


if __name__ == "__main__":
    main()
