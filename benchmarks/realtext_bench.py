"""Real-text training row on the chip (VERDICT r3 #4's BASELINE row).

Trains a GPT-2-class byte-level LM on the committed REAL-prose corpus
(tests/model/fixtures/realtext_*.txt.xz — human-written documentation
English) and reports the held-out perplexity trajectory: the loss curve
on real data, not synthetic tokens. Byte-level vocab because the
environment has no egress for a pretrained BPE; the text statistics are
genuinely Zipfian either way.

Run ON the chip: python benchmarks/realtext_bench.py [--steps N]
"""

from __future__ import annotations

import argparse
import json
import lzma
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

FIXTURES = os.path.join(os.path.dirname(__file__), "..", "tests", "model",
                        "fixtures")


def load(split):
    with lzma.open(os.path.join(FIXTURES, f"realtext_{split}.txt.xz"),
                   "rt") as f:
        return np.frombuffer(f.read().encode("utf-8"), np.uint8)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--eval-every", type=int, default=50)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from _bench_util import enable_persistent_cache

    enable_persistent_cache()  # before the first compile

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    train, val = load("train"), load("val")
    cfg = GPT2Config(vocab_size=256, n_positions=args.seq, n_embd=768,
                     n_layer=12, n_head=12, dtype=jnp.bfloat16)
    engine, _, _, _ = ds.initialize(
        model=GPT2LMHeadModel(cfg),
        config={"train_micro_batch_size_per_gpu": args.batch,
                "gradient_accumulation_steps": 1,
                "zero_optimization": {"stage": 0},
                "optimizer": {"type": "AdamW",
                              "params": {"lr": 3e-4, "weight_decay": 0.01}},
                "scheduler": {"type": "WarmupLR",
                              "params": {"warmup_num_steps": 50}},
                "bf16": {"enabled": True},
                "gradient_clipping": 1.0, "steps_per_print": 10 ** 9})

    rng = np.random.default_rng(0)

    def batch_from(data, n, seed_rng):
        starts = seed_rng.integers(0, len(data) - args.seq - 1, n)
        return {"input_ids": np.stack(
            [data[s:s + args.seq] for s in starts]).astype(np.int32)}

    val_rng = np.random.default_rng(7)
    val_batches = [batch_from(val, args.batch, val_rng) for _ in range(4)]
    eval_fn = None

    def val_ppl():
        nonlocal eval_fn
        if eval_fn is None:
            eval_fn = engine.eval_batch_fn()
        losses = [float(eval_fn(engine.state["params"], b))
                  for b in val_batches]
        return float(np.exp(np.mean(losses)))

    traj = []
    step_walls = []
    for step in range(1, args.steps + 1):
        ts = time.perf_counter()
        loss = float(engine.train_batch(
            batch=batch_from(train, args.batch, rng)))
        step_walls.append(time.perf_counter() - ts)
        if step == 1 or step % args.eval_every == 0:
            ppl = val_ppl()
            traj.append({"step": step, "train_loss": round(loss, 4),
                         "val_ppl": round(ppl, 2)})
            print(f"[realtext] {traj[-1]}", flush=True)
    # steady-state rate: median step wall, warmup/compile excluded (and
    # eval time never counted — it is outside the per-step windows)
    med = float(np.median(step_walls[3:] or step_walls))
    tok_s = args.batch * args.seq / med

    result = {
        "model": "gpt2-125m-class byte-level (vocab 256)",
        "corpus": "real prose fixture (2.8 MB train / 0.2 MB val)",
        "batch": args.batch, "seq": args.seq, "steps": args.steps,
        "trajectory": traj,
        "final_val_ppl": traj[-1]["val_ppl"],
        "tokens_per_s_steady": round(tok_s, 1),
        "ppl_uniform_ceiling": 256.0,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "realtext_results.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(f"[realtext] final val ppl {result['final_val_ppl']} "
          f"({tok_s:.0f} tok/s steady) -> {path}", flush=True)


if __name__ == "__main__":
    main()
