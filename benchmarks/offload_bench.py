"""NVMe offload step: pipelined (async, write/compute overlapped) vs
serialized I/O — the measurement behind the swap-tier overlap claim
(reference PipelinedOptimizerSwapper's motivation).

    python benchmarks/offload_bench.py --mb 256

Serialized mode is the same step with a 1-thread AIO handle and a drain
after every submit batch (no intra-phase overlap, no write/compute
overlap).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def make_opt(nbytes_total: int, n_leaves: int, serial: bool, tmpdir: str):
    from deepspeed_tpu.ops.aio import AioHandle
    from deepspeed_tpu.runtime.zero.offload import OffloadedOptimizer
    from deepspeed_tpu.runtime.zero.offload_config import (
        DeepSpeedZeroOffloadOptimizerConfig,
    )

    per_leaf = nbytes_total // n_leaves // 4
    params = {f"w{i}": np.random.default_rng(i).standard_normal(
        per_leaf).astype(np.float32) for i in range(n_leaves)}
    cfg = DeepSpeedZeroOffloadOptimizerConfig(
        device="nvme", nvme_path=tmpdir, buffer_count=1 if serial else 4)
    opt = OffloadedOptimizer(params, {"lr": 1e-3}, cfg)
    if serial:
        # cripple the handle: 1 thread and a wait after every submit → the
        # fully synchronous baseline. SAME o_direct routing as the
        # pipelined handle — the comparison must vary only the overlap,
        # not the device path.
        was_od = opt._aio.o_direct
        opt._aio.close()
        opt._aio = AioHandle(num_threads=1, o_direct=was_od)
        real_pwrite = opt._aio.async_pwrite
        real_pread = opt._aio.async_pread

        def sync_pwrite(a, path, offset=0):
            t = real_pwrite(a, path, offset)
            opt._aio.wait()
            return t

        def sync_pread(a, path, offset=0):
            t = real_pread(a, path, offset)
            opt._aio.wait()
            return t

        opt._aio.async_pwrite = sync_pwrite
        opt._aio.async_pread = sync_pread
        # (the on-disk files were seeded by __init__; both modes read the
        # same content — only the step-time I/O goes through this handle)
    return opt, params


def bench(serial: bool, nbytes_total: int, n_leaves: int, tmpdir: str,
          steps: int = 3):
    opt, params = make_opt(nbytes_total, n_leaves, serial, tmpdir)
    grads = {k: np.ones_like(v) * 1e-3 for k, v in params.items()}
    opt.step(grads, 1e-3, 1, None)  # warmup
    phase_sums: dict = {}
    t0 = time.perf_counter()
    for s in range(steps):
        opt.step(grads, 1e-3, s + 2, None)
        for k, v in opt.last_timings.items():
            phase_sums[k] = phase_sums.get(k, 0.0) + v
    dt = (time.perf_counter() - t0) / steps
    return dt, {k: v / steps for k, v in phase_sums.items()}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=256)
    ap.add_argument("--leaves", type=int, default=16)
    ap.add_argument("--dir", default="/tmp/ds_offload_bench")
    ap.add_argument("--sim-bw-mbps", type=int, default=0,
                    help="simulate a device of this aggregate bandwidth "
                         "(chunk-proportional off-CPU sleeps in the AIO "
                         "workers) — models a real NVMe where I/O waits "
                         "idle the core; 0 = measure the real filesystem")
    args = ap.parse_args()
    import os
    import shutil

    if args.sim_bw_mbps > 0:
        os.environ["DS_AIO_SIM_US_PER_MB"] = str(10 ** 6 // args.sim_bw_mbps)
    shutil.rmtree(args.dir, ignore_errors=True)
    os.makedirs(args.dir)
    nbytes = args.mb << 20
    t_async, timings_async = bench(False, nbytes, args.leaves, args.dir)
    shutil.rmtree(args.dir)
    os.makedirs(args.dir)
    t_serial, timings_serial = bench(True, nbytes, args.leaves, args.dir)
    print(json.dumps({
        "master_mb": args.mb, "leaves": args.leaves,
        "sim_bw_mbps": args.sim_bw_mbps or None,
        "pipelined_step_s": round(t_async, 3),
        "pipelined_phases": {k: round(v, 3) for k, v in timings_async.items()},
        "serial_step_s": round(t_serial, 3),
        "serial_phases": {k: round(v, 3) for k, v in timings_serial.items()},
        "speedup": round(t_serial / t_async, 2),
    }, indent=2))


if __name__ == "__main__":
    main()
