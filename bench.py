"""Benchmark: GPT-2 125M causal-LM training throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/s/chip", "vs_baseline": N}

``vs_baseline`` compares achieved model TFLOPS against the reference's
headline single-device number: 64 TFLOPS/GPU for BERT-Large pretraining with
DeepSpeed's fused kernels on V100-32GB (BASELINE.md row 1,
reference docs/_tutorials/bert-pretraining.md:392).
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel, gpt2_config
    from deepspeed_tpu.runtime.utils import count_parameters

    SEQ = 1024
    # tuned on v5e-1: large per-dispatch work amortizes tunnel/dispatch
    # latency; selective remat ("dots": save matmuls, recompute
    # elementwise) fits mbs=16 in HBM with the best recompute trade
    MICRO_BS = 16
    GAS = 16

    cfg = gpt2_config("gpt2-125m", n_positions=SEQ, dtype=jnp.bfloat16,
                      remat=True, remat_policy="dots")
    model = GPT2LMHeadModel(cfg)
    config = {
        "train_micro_batch_size_per_gpu": MICRO_BS,
        "gradient_accumulation_steps": GAS,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "Adam", "params": {"lr": 6e-4, "weight_decay": 0.1}},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)

    rng = np.random.default_rng(0)

    def make_batch():
        return {"input_ids": rng.integers(
            0, cfg.vocab_size, (engine.train_batch_size(), SEQ)).astype(np.int32)}

    # warmup (compile)
    for _ in range(2):
        loss = engine.train_batch(batch=make_batch())
    jax.block_until_ready(loss)

    steps = 5
    batches = [make_batch() for _ in range(steps)]
    t0 = time.perf_counter()
    for b in batches:
        loss = engine.train_batch(batch=b)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    tokens_per_step = engine.train_batch_size() * SEQ
    tokens_per_sec_chip = tokens_per_step * steps / dt / n_chips

    # model flops per token: fwd+bwd ≈ 6N dense + attention term
    n_params = count_parameters(engine.state["params"])
    flops_per_token = 6 * n_params + 12 * cfg.n_layer * cfg.n_embd * SEQ
    achieved_tflops = tokens_per_sec_chip * flops_per_token / 1e12

    print(json.dumps({
        "metric": "GPT-2 125M seq1024 bf16 ZeRO-1 training throughput",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(achieved_tflops / 64.0, 3),
        "detail": {
            "achieved_model_tflops_per_chip": round(achieved_tflops, 2),
            "baseline": "DeepSpeed BERT-Large 64 TFLOPS on 1xV100-32GB",
            "n_chips": n_chips,
            "loss": float(loss),
        },
    }))


if __name__ == "__main__":
    main()
